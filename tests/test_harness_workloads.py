"""End-to-end workload tests: the five challenges on the virtual-clock
harness — the in-repo equivalent of the reference's Maelstrom runs
(survey §4)."""

from gossip_glomers_tpu.harness import random_partitions
from gossip_glomers_tpu.harness.workloads import (run_broadcast, run_counter,
                                                  run_echo, run_kafka,
                                                  run_unique_ids)


def test_echo():
    res = run_echo(n_ops=10)
    assert res.ok, res.details


def test_unique_ids():
    res = run_unique_ids(n_nodes=3, n_ops=100)
    assert res.ok, res.details


def test_broadcast_tree_no_faults():
    res = run_broadcast(n_nodes=25, topology="tree", n_values=30,
                        quiescence=8.0)
    assert res.ok, res.details
    # Structural bound: the eager flood costs 2 messages per tree edge per
    # value (24 edges -> 48), plus bounded anti-entropy overhead.  (The
    # reference README's "< 20 msgs/op" divides by *all* client ops
    # including reads, which cost no server messages; our denominator is
    # broadcast ops only, so the comparable bound is higher.)
    assert res.stats["msgs_per_op"] < 80, res.stats


def test_broadcast_grid_latency_partitions():
    # Maelstrom 3d/3e shape: grid topology, 100 ms link latency, random
    # partitions while ops are in flight (BASELINE.json config 2).
    parts = random_partitions([f"n{i}" for i in range(25)], t_end=10.0,
                              period=4.0, duration=1.5, seed=3)
    res = run_broadcast(n_nodes=25, topology="grid", n_values=25,
                        rate=5.0, quiescence=20.0, latency=0.1,
                        partitions=parts, seed=3)
    assert res.ok, res.details


def test_broadcast_latency_headline():
    # reference headline: < 500 ms broadcast op latency with 100 ms links
    # (README.md:16) — on a tree, ack comes after one hop back.
    res = run_broadcast(n_nodes=25, topology="tree", n_values=20,
                        rate=5.0, quiescence=10.0, latency=0.1)
    assert res.ok, res.details
    assert res.stats["broadcast_latency_max"] < 0.5, res.stats


def test_counter():
    res = run_counter(n_nodes=3, n_ops=40, quiescence=8.0)
    assert res.ok, res.details


def test_counter_partitioned():
    # BASELINE.json config 3: partitioned g-counter, read after quiescence.
    nodes = [f"n{i}" for i in range(3)]
    parts = random_partitions(nodes, t_end=6.0, period=3.0, duration=1.2,
                              seed=7, include=["seq-kv"])
    res = run_counter(n_nodes=3, n_ops=40, quiescence=15.0,
                      partitions=parts, seed=7)
    assert res.ok, res.details


def test_kafka():
    res = run_kafka(n_nodes=2, n_keys=4, n_ops=100)
    assert res.ok, res.details


def test_broadcast_mix_converges_and_accounts():
    from gossip_glomers_tpu.harness.workloads import run_broadcast_mix

    res = run_broadcast_mix(n_nodes=25, topology="tree", rate=50.0,
                            duration=8.0, read_share=0.5, seed=0)
    assert res.ok
    assert res.details["n_ops"] == 400
    # eager flood on tree25 costs 2*(n-1)=48 server msgs per broadcast;
    # at ~50% broadcast share the all-ops accounting lands near 24-27
    # (+ anti-entropy) — the same order as the reference's README claim,
    # whose exact value depends on the op mix.
    assert 15.0 < res.stats["msgs_per_op"] < 40.0
