"""End-to-end workload tests: the five challenges on the virtual-clock
harness — the in-repo equivalent of the reference's Maelstrom runs
(survey §4)."""

from gossip_glomers_tpu.harness import random_partitions
from gossip_glomers_tpu.harness.workloads import (run_broadcast, run_counter,
                                                  run_echo, run_kafka,
                                                  run_unique_ids)


def test_echo():
    res = run_echo(n_ops=10)
    assert res.ok, res.details


def test_unique_ids():
    res = run_unique_ids(n_nodes=3, n_ops=100)
    assert res.ok, res.details


def test_broadcast_tree_no_faults():
    res = run_broadcast(n_nodes=25, topology="tree", n_values=30,
                        quiescence=8.0)
    assert res.ok, res.details
    # Structural bound: the eager flood costs 2 messages per tree edge per
    # value (24 edges -> 48), plus bounded anti-entropy overhead.  (The
    # reference README's "< 20 msgs/op" divides by *all* client ops
    # including reads, which cost no server messages; our denominator is
    # broadcast ops only, so the comparable bound is higher.)
    assert res.stats["msgs_per_op"] < 80, res.stats


def test_broadcast_grid_latency_partitions():
    # Maelstrom 3d/3e shape: grid topology, 100 ms link latency, random
    # partitions while ops are in flight (BASELINE.json config 2).
    parts = random_partitions([f"n{i}" for i in range(25)], t_end=10.0,
                              period=4.0, duration=1.5, seed=3)
    res = run_broadcast(n_nodes=25, topology="grid", n_values=25,
                        rate=5.0, quiescence=20.0, latency=0.1,
                        partitions=parts, seed=3)
    assert res.ok, res.details


def test_broadcast_latency_headline():
    # reference headline: < 500 ms broadcast op latency with 100 ms links
    # (README.md:16) — on a tree, ack comes after one hop back.
    res = run_broadcast(n_nodes=25, topology="tree", n_values=20,
                        rate=5.0, quiescence=10.0, latency=0.1)
    assert res.ok, res.details
    assert res.stats["broadcast_latency_max"] < 0.5, res.stats


def test_counter():
    res = run_counter(n_nodes=3, n_ops=40, quiescence=8.0)
    assert res.ok, res.details


def test_counter_partitioned():
    # BASELINE.json config 3: partitioned g-counter, read after quiescence.
    nodes = [f"n{i}" for i in range(3)]
    parts = random_partitions(nodes, t_end=6.0, period=3.0, duration=1.2,
                              seed=7, include=["seq-kv"])
    res = run_counter(n_nodes=3, n_ops=40, quiescence=15.0,
                      partitions=parts, seed=7)
    assert res.ok, res.details


def test_counter_stale_seq_kv():
    """VERDICT r2 item 4: seq-kv serving genuinely stale reads — the
    consistency level the reference's counter is written against
    (add.go:97-118).  A stale readKV makes the flush CAS fail
    precondition (code 22) and re-enter the jittered retry
    (add.go:80-88); the final read-after-quiescence sum must still be
    exact, with strictly more CAS retries than the no-staleness run."""
    from gossip_glomers_tpu.protocol import PRECONDITION_FAILED

    fresh = run_counter(n_nodes=3, n_ops=40, quiescence=12.0,
                        stale_read_prob=0.0, seed=11)
    stale = run_counter(n_nodes=3, n_ops=40, quiescence=12.0,
                        stale_read_prob=0.6, seed=11)
    assert fresh.ok, fresh.details
    assert stale.ok, stale.details          # sum survives staleness
    fresh_retries = fresh.stats["kv_errors_by_code"].get(
        PRECONDITION_FAILED, 0)
    stale_retries = stale.stats["kv_errors_by_code"].get(
        PRECONDITION_FAILED, 0)
    assert stale_retries > fresh_retries, (fresh_retries, stale_retries)
    assert stale_retries > 0


def test_kafka():
    res = run_kafka(n_nodes=2, n_keys=4, n_ops=100)
    assert res.ok, res.details


def test_broadcast_mix_converges_and_accounts():
    from gossip_glomers_tpu.harness.workloads import run_broadcast_mix

    res = run_broadcast_mix(n_nodes=25, topology="tree", rate=50.0,
                            duration=8.0, read_share=0.5, seed=0)
    assert res.ok
    assert res.details["n_ops"] == 400
    # eager flood on tree25 costs 2*(n-1)=48 server msgs per broadcast;
    # at ~50% broadcast share the all-ops accounting lands near 24-27
    # (+ anti-entropy) — the same order as the reference's README claim,
    # whose exact value depends on the op mix.
    assert 15.0 < res.stats["msgs_per_op"] < 40.0


def test_kafka_fault_campaign_contention_partitions_and_drops():
    """VERDICT r1 item 4: the kafka retry machinery exercised end-to-end
    — CAS races on hot keys (logmap.go:255-285), the code-21 commit
    create-race (logmap.go:46-52), timeouts from a partitioned node,
    and replicate_msg loss — with offsets still unique and the checker
    green."""
    from gossip_glomers_tpu.harness.faults import (PartitionSchedule,
                                                   PartitionWindow)
    from gossip_glomers_tpu.harness.workloads import run_kafka_faults

    others = [f"n{i}" for i in range(3)] + ["lin-kv"]
    parts = PartitionSchedule([PartitionWindow(4.0, 9.0,
                                               [["n3"], others])])
    res = run_kafka_faults(n_nodes=4, n_keys=2, n_bursts=12,
                           latency=0.05, partitions=parts, seed=3)
    assert res.ok, res.details
    kv = res.stats["kv_by_type"]
    acked = res.details["n_acked"]
    assert acked > 20
    # contention proof: strictly more CAS ops than acked sends — lost
    # races re-enter the allocation loop (plus commit-dance CAS traffic)
    assert kv["cas"] > acked, (kv, acked)
    # lost CAS races got error replies (code 22 from lin-kv)
    assert kv.get("error", 0) > 0, kv
    # the partitioned node's KV ops timed out -> failed send replies
    assert res.details["n_send_errors"] > 0
    # replicate_msg / KV traffic was actually dropped by the partition
    assert res.stats["dropped_msgs"] > 0


def test_kafka_fault_campaign_no_partition_still_contends():
    from gossip_glomers_tpu.harness.workloads import run_kafka_faults

    res = run_kafka_faults(n_nodes=5, n_keys=1, n_bursts=6,
                           latency=0.04, seed=1)
    assert res.ok, res.details
    assert res.details["n_send_errors"] == 0
    assert res.details["n_acked"] == 30          # every send acked
    kv = res.stats["kv_by_type"]
    # 5-way bursts on one key: ranks 0..4 per burst, so the serialized
    # CAS ladder fires well above one cas per send
    assert kv["cas"] >= res.details["n_acked"] * 2


def test_workloads_replay_bit_identical():
    """All randomness is seeded (survey §7 'hard parts': deterministic
    replay of an asynchronous system): running any workload twice with
    the same seed must reproduce the EXACT ledger — totals, per-type
    splits, drops, op latencies — not just the same pass/fail."""
    from gossip_glomers_tpu.harness import random_partitions
    from gossip_glomers_tpu.harness.workloads import (run_broadcast,
                                                      run_counter,
                                                      run_kafka,
                                                      run_kafka_faults,
                                                      run_unique_ids)

    def parts9():
        return random_partitions([f"n{i}" for i in range(9)],
                                 t_end=6.0, seed=5)

    runs = [
        lambda: run_unique_ids(n_nodes=3, n_ops=40, seed=3),
        lambda: run_broadcast(n_nodes=9, topology="grid", n_values=12,
                              rate=30.0, latency=0.05, quiescence=6.0,
                              partitions=parts9(), seed=5),
        lambda: run_counter(n_nodes=3, n_ops=24, rate=20.0,
                            quiescence=6.0, stale_read_prob=0.3,
                            seed=7),
        lambda: run_kafka(n_nodes=2, n_keys=3, n_ops=50, seed=11),
        lambda: run_kafka_faults(n_nodes=4, n_keys=2, n_bursts=4,
                                 latency=0.03, seed=13),
    ]
    for make in runs:
        a, b = make(), make()
        assert a.ok == b.ok
        assert a.stats == b.stats, (a.stats, b.stats)
        assert a.details == b.details


def test_workload_cli_maelstrom_ux():
    """`python -m gossip_glomers_tpu.harness test -w ...` mirrors the
    Maelstrom CLI the reference is driven by (README.md:7-10): runs the
    workload, prints a JSON stats line + verdict, exits 0/1."""
    import json
    import subprocess
    import sys

    def run(*args):
        p = subprocess.run(
            [sys.executable, "-m", "gossip_glomers_tpu.harness",
             "test", *args],
            capture_output=True, text=True, timeout=120)
        return p

    p = run("-w", "broadcast", "--node-count", "9", "--topology", "grid",
            "--rate", "10", "--time-limit", "6", "--latency", "0.05",
            "--nemesis", "partition", "--seed", "3")
    assert p.returncode == 0, p.stderr
    stats = json.loads(p.stdout.splitlines()[0])
    assert stats["ok"] and stats["msgs_per_op"] > 0
    assert stats["dropped_msgs"] > 0      # the nemesis really fired
    assert "Everything looks good!" in p.stdout

    p = run("-w", "counter", "--rate", "10", "--time-limit", "6",
            "--nemesis", "partition", "--seed", "7")
    assert p.returncode == 0, p.stderr
    stats = json.loads(p.stdout.splitlines()[0])
    assert stats["ok"]
    assert stats["dropped_msgs"] > 0      # seq-kv reachability was cut

    p = run("-w", "unique-ids", "--rate", "20", "--time-limit", "1")
    assert p.returncode == 0, p.stderr
    assert json.loads(p.stdout.splitlines()[0])["ok"]

    # kafka fault campaign: nemesis + the knossos-style per-key
    # certification verdict surfaced in the summary line
    p = run("-w", "kafka-faults", "--node-count", "4",
            "--nemesis", "partition", "--time-limit", "12",
            "--seed", "2")
    assert p.returncode == 0, p.stderr
    stats = json.loads(p.stdout.splitlines()[0])
    assert stats["ok"] and stats["linearizable"] is True
    assert stats["dropped_msgs"] > 0

    # a flag the workload cannot honor is a usage error, not a silent
    # green run
    p = run("-w", "kafka", "--topology", "ring")
    assert p.returncode == 2
    p = run("-w", "echo", "--nemesis", "partition")
    assert p.returncode == 2
    # a nemesis window that cannot fire inside --time-limit is an error
    p = run("-w", "broadcast", "--time-limit", "2",
            "--nemesis", "partition")
    assert p.returncode == 2


def test_latency_percentiles_reported():
    # Maelstrom publishes op-latency distributions; the harness stats
    # expose the nearest-rank p50/p95/p99 over the virtual clock
    res = run_broadcast(n_nodes=9, topology="tree", n_values=20,
                        rate=10.0, quiescence=6.0, latency=0.1)
    s = res.stats
    assert 0.0 < s["latency_p50"] <= s["latency_p95"] \
        <= s["latency_p99"] <= s["latency_max"]
    # tree ack = one hop out + one back at 0.1 s/hop
    assert abs(s["latency_p50"] - 0.2) < 1e-6


def test_latency_percentile_nearest_rank():
    # pinned against hand-computed nearest-rank values on DISTINCT
    # latencies (the CLI-level test above has identical latencies and
    # cannot catch an indexing error)
    from gossip_glomers_tpu.harness.network import VirtualNetwork
    from gossip_glomers_tpu.harness.workloads import _stats

    net = VirtualNetwork()
    net.ledger.op_latencies = [0.1 * i for i in range(1, 21)]
    s = _stats(net, 20)
    assert abs(s["latency_p50"] - 1.0) < 1e-9    # ceil(10)-1 -> 10th
    assert abs(s["latency_p95"] - 1.9) < 1e-9    # ceil(19)-1 -> 19th
    assert abs(s["latency_p99"] - 2.0) < 1e-9    # ceil(19.8)-1 -> 20th
    assert abs(s["latency_max"] - 2.0) < 1e-9
