"""Linearizability checker: synthetic histories + a live KV trace."""

from gossip_glomers_tpu.harness import tracing
from gossip_glomers_tpu.harness.linearize import (KEY_MISSING, Op,
                                                  check_linearizable,
                                                  history_from_kv_trace)


def test_sequential_history_ok():
    h = [Op(0, 1, "write", (5,), "ok"),
         Op(2, 3, "read", (), 5),
         Op(4, 5, "cas", (5, 7), "ok"),
         Op(6, 7, "read", (), 7)]
    ok, details = check_linearizable(h)
    assert ok
    assert details["order"] == [0, 1, 2, 3]


def test_concurrent_reordering_ok():
    # read of 2 overlaps both writes: legal by ordering write(2) first
    h = [Op(0, 10, "write", (1,), "ok"),
         Op(0, 10, "write", (2,), "ok"),
         Op(0, 10, "read", (), 2),
         Op(11, 12, "read", (), 1)]
    ok, details = check_linearizable(h)
    assert ok


def test_stale_read_not_linearizable():
    # write(1) completed before read invoked, but read sees the initial
    # missing marker — no legal order
    h = [Op(0, 1, "write", (1,), "ok"),
         Op(2, 3, "read", (), KEY_MISSING)]
    ok, _ = check_linearizable(h)
    assert not ok


def test_cas_semantics_enforced():
    # two CAS from the same value cannot both succeed
    h = [Op(0, 1, "write", (1,), "ok"),
         Op(2, 10, "cas", (1, 2), "ok"),
         Op(2, 10, "cas", (1, 3), "ok")]
    ok, _ = check_linearizable(h)
    assert not ok
    # ...but one succeeding and one failing is fine either way
    h2 = [Op(0, 1, "write", (1,), "ok"),
          Op(2, 10, "cas", (1, 2), "ok"),
          Op(2, 10, "cas", (1, 3), "fail")]
    ok2, _ = check_linearizable(h2)
    assert ok2


def test_real_time_order_respected():
    # value must go 1 -> 2; a later read of 1 after reading 2 is illegal
    h = [Op(0, 1, "write", (1,), "ok"),
         Op(2, 3, "write", (2,), "ok"),
         Op(4, 5, "read", (), 2),
         Op(6, 7, "read", (), 1)]
    ok, _ = check_linearizable(h)
    assert not ok


def test_missing_then_create_cas():
    h = [Op(0, 1, "cas", (0, 0), "missing"),
         Op(2, 3, "write", (0,), "ok"),   # the create-CAS, as modeled
         Op(4, 5, "cas", (0, 4), "ok"),
         Op(6, 7, "read", (), 4)]
    ok, _ = check_linearizable(h)
    assert ok


def test_counter_kv_trace_is_linearizable():
    # live history: the counter workload's seq-kv traffic under latency,
    # extracted from a traced virtual-network run
    from gossip_glomers_tpu.harness.network import VirtualNetwork
    from gossip_glomers_tpu.harness.services import KVService
    from gossip_glomers_tpu.models import CounterProgram
    from gossip_glomers_tpu.utils.config import NetConfig

    net = VirtualNetwork(NetConfig(latency=0.02, seed=1))
    for i in range(3):
        net.spawn(f"n{i}", CounterProgram())
    net.add_service(KVService(net, "seq-kv"))
    trace = tracing.enable_trace(net)
    net.init_cluster()
    client = net.client("c1")
    for i in range(12):
        client.rpc(f"n{i % 3}", {"type": "add", "delta": i + 1})
        net.run_for(0.1)
    net.run_for(5.0)

    history = history_from_kv_trace(trace, "seq-kv", key="value")
    assert len(history) >= 6, "expected real KV traffic"
    ok, details = check_linearizable(history)
    assert ok, details


def test_indeterminate_write_both_branches():
    inf = float("inf")
    # dropped-reply write: legal if it DID happen (read sees 9)...
    h = [Op(0, inf, "write", (9,), None, maybe=True),
         Op(1, 2, "read", (), 9)]
    ok, _ = check_linearizable(h)
    assert ok
    # ...and legal if it did NOT happen (read sees missing)
    h2 = [Op(0, inf, "write", (9,), None, maybe=True),
          Op(1, 2, "read", (), KEY_MISSING)]
    ok2, _ = check_linearizable(h2)
    assert ok2
    # but a read of a value nobody could have written still fails
    h3 = [Op(0, inf, "write", (9,), None, maybe=True),
          Op(1, 2, "read", (), 7)]
    ok3, _ = check_linearizable(h3)
    assert not ok3


def test_zero_width_concurrent_windows():
    # identical zero-width windows are concurrent, not mutually
    # preceding — both orders must be considered
    h = [Op(1.0, 1.0, "write", (1,), "ok"),
         Op(1.0, 1.0, "write", (2,), "ok"),
         Op(2.0, 3.0, "read", (), 1)]
    ok, _ = check_linearizable(h)
    assert ok


def test_dropped_kv_reply_history_still_checkable():
    # partition drops seq-kv replies mid-run: unacked CAS/writes become
    # maybe-ops and the history must still check out
    from gossip_glomers_tpu.harness.faults import (PartitionSchedule,
                                                   PartitionWindow)
    from gossip_glomers_tpu.harness.network import VirtualNetwork
    from gossip_glomers_tpu.harness.services import KVService
    from gossip_glomers_tpu.models import CounterProgram
    from gossip_glomers_tpu.utils.config import NetConfig

    net = VirtualNetwork(NetConfig(latency=0.02, seed=5))
    for i in range(3):
        net.spawn(f"n{i}", CounterProgram())
    net.add_service(KVService(net, "seq-kv"))
    parts = PartitionSchedule([PartitionWindow(
        0.4, 1.2, [["n0", "n1"], ["n2", "seq-kv"]])])
    net.drop_fn = parts.drop_fn()
    trace = tracing.enable_trace(net)
    net.init_cluster()
    client = net.client("c1")
    for i in range(10):
        client.rpc(f"n{i % 3}", {"type": "add", "delta": 1})
        net.run_for(0.2)
    net.run_for(4.0)

    history = history_from_kv_trace(trace, "seq-kv", key="value")
    assert len(history) >= 6
    ok, details = check_linearizable(history)
    assert ok, details


def test_create_cas_exact_semantics():
    # ccas succeeds from MISSING (creating at `to`)...
    h = [Op(0, 1, "ccas", (1, 1), "ok"),
         Op(2, 3, "read", (), 1)]
    ok, _ = check_linearizable(h)
    assert ok
    # ...and from a matching frm on an existing key
    h2 = [Op(0, 1, "write", (3,), "ok"),
          Op(2, 3, "ccas", (3, 4), "ok"),
          Op(4, 5, "read", (), 4)]
    ok2, _ = check_linearizable(h2)
    assert ok2
    # but a successful ccas with a mismatched frm on an existing key is
    # now rejected (the old write(to) model wrongly accepted this)
    h3 = [Op(0, 1, "write", (3,), "ok"),
          Op(2, 3, "ccas", (99, 4), "ok")]
    ok3, _ = check_linearizable(h3)
    assert not ok3
    # a failing ccas must have seen a mismatched existing value
    h4 = [Op(0, 1, "write", (3,), "ok"),
          Op(2, 3, "ccas", (99, 4), "fail"),
          Op(4, 5, "read", (), 3)]
    ok4, _ = check_linearizable(h4)
    assert ok4


def test_long_history_no_recursion_limit():
    # thousands of sequential ops: the explicit-stack DFS must decide
    # this cleanly where Python-frame recursion would blow the limit
    h = []
    v = KEY_MISSING
    for i in range(3000):
        h.append(Op(2 * i, 2 * i + 1, "write", (i,), "ok"))
    h.append(Op(6002, 6003, "read", (), 2999))
    ok, details = check_linearizable(h)
    assert ok
    assert details["order"] is not None and len(details["order"]) == 3001


# -- checker wired into the workloads (knossos-style certification) -----


def test_workloads_certify_kv_linearizability():
    # run_counter / run_kafka / run_kafka_faults now run the checker
    # over the captured KV trace; healthy services must certify
    from gossip_glomers_tpu.harness import random_partitions
    from gossip_glomers_tpu.harness.workloads import (run_counter,
                                                      run_kafka,
                                                      run_kafka_faults)

    res = run_counter(n_nodes=3, n_ops=30, latency=0.02, seed=5)
    assert res.ok and res.details["linearizable"]
    assert res.details["lin_by_key"]["value"]["n_ops"] > 10
    # fully decided pass: no key stopped at the state budget
    assert res.details["lin_unknown_keys"] == 0

    res = run_kafka(n_nodes=2, n_keys=2, n_ops=60, seed=1)
    assert res.ok and res.details["linearizable"]

    nodes = [f"n{i}" for i in range(4)]
    res = run_kafka_faults(
        n_nodes=4, partitions=random_partitions(
            nodes, t_end=12.0, seed=2, include=["lin-kv"]), seed=2)
    assert res.ok and res.details["linearizable"]
    assert sum(v["n_ops"] for v in res.details["lin_by_key"].values()) > 50


def test_linearize_check_bites_on_stale_cas_bug(monkeypatch):
    # mutation test: inject a stale-CAS bug into the KV service (a CAS
    # against a stale `from` succeeds anyway — the classic lost-update
    # bug) and prove the wired-in checker FAILS the workload.  The
    # injection is seeded and service-side only; nodes are untouched.
    import random as _random

    from gossip_glomers_tpu.harness import workloads
    from gossip_glomers_tpu.harness.services import KVService
    from gossip_glomers_tpu.harness.workloads import run_kafka_faults

    class StaleCASKV(KVService):
        def __init__(self, *a, **kw):
            super().__init__(*a, **kw)
            self._bug_rng = _random.Random(1234)

        def deliver(self, msg):
            body = msg.body
            key = str(body.get("key"))
            if (msg.type == "cas" and key in self.store
                    and self.store[key] != body.get("from")
                    and self._bug_rng.random() < 0.5):
                # BUG: accept the CAS against a stale expectation
                self.store[key] = body.get("to")
                self._reply(msg, {"type": "cas_ok"})
                return
            super().deliver(msg)

    monkeypatch.setattr(workloads, "KVService", StaleCASKV)
    res = run_kafka_faults(n_nodes=4, seed=3)
    assert res.details["linearizable"] is False
    bad = [k for k, v in res.details["lin_by_key"].items() if not v["ok"]]
    assert bad, "at least one key history must fail certification"


def test_state_budget_yields_unknown_not_hang():
    # a pathological history (many concurrent indeterminate CASes) is
    # exponential for Wing-Gong; the in-workload certification must
    # stop at the max_states budget with verdict "unknown" (not a
    # failure: budget exhaustion is not a linearizability violation)
    # concurrent indeterminate writes + a read of a never-written
    # value: every order is illegal, so the DFS must backtrack through
    # exponentially many dead states before it could prove "fail"
    ops = [Op(0.0, float("inf"), "write", (i,), None, maybe=True)
           for i in range(12)]
    ops.append(Op(0.0, 1.0, "read", (), 999))
    ok, d = check_linearizable(ops, max_states=5)
    assert ok is True
    assert d["verdict"] == "unknown"
    assert d["states_explored"] <= 5
    # verdicts on decided searches stay "ok"/"fail"
    ok2, d2 = check_linearizable(
        [Op(0.0, 1.0, "write", (7,), "ok"),
         Op(2.0, 3.0, "read", (), 7)])
    assert ok2 and d2["verdict"] == "ok"
    ok3, d3 = check_linearizable(
        [Op(0.0, 1.0, "write", (7,), "ok"),
         Op(2.0, 3.0, "read", (), 8)])
    assert not ok3 and d3["verdict"] == "fail"


def test_lin_unknown_keys_aggregate(monkeypatch):
    # ADVICE r5: a budget-exceeded search returns ok=True with a
    # per-key "unknown" verdict, so the top-level aggregate alone
    # could not distinguish a fully decided pass from one that gave
    # up — _check_kv_linearizable now surfaces the undecided count
    from gossip_glomers_tpu.harness import workloads

    def fake_histories(trace, service_id):
        return {"a": ["decided"], "b": ["exceeded"]}

    def fake_check(hist):
        if hist == ["decided"]:
            return True, {"n_ops": 3, "verdict": "ok"}
        return True, {"n_ops": 9, "verdict": "unknown"}

    monkeypatch.setattr(workloads, "histories_from_kv_trace",
                        fake_histories)
    monkeypatch.setattr(workloads, "check_linearizable", fake_check)
    details = {}
    ok = workloads._check_kv_linearizable([], "lin-kv", details)
    assert ok is True            # budget exhaustion is not a violation
    assert details["linearizable"] is True
    assert details["lin_unknown_keys"] == 1
    assert details["lin_by_key"]["b"]["verdict"] == "unknown"
