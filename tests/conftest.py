"""Test env: force an 8-device virtual CPU mesh before any backend init.

Multi-chip TPU hardware is not available in CI; sharding tests run over
XLA's virtual host devices (same SPMD partitioner, same collectives).

Note: a sitecustomize in this image registers the TPU PJRT plugin at
interpreter start and forces the platform, so plain env vars are not
enough — ``jax.config.update`` after import wins, as long as XLA_FLAGS
carries the virtual-device count before the CPU backend initializes.
"""

import os
import sys

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ["JAX_PLATFORMS"] = "cpu"

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The package is installed (pip install -e ., see pyproject.toml); fall
# back to the repo checkout only if running against a bare tree.
try:
    import gossip_glomers_tpu  # noqa: F401
except ImportError:  # pragma: no cover
    sys.path.insert(0,
                    os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from gossip_glomers_tpu.utils.compile_cache import (  # noqa: E402
    enable_compile_cache)

enable_compile_cache()
