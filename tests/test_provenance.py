"""Causal provenance tracing (tpu_sim/provenance.py +
harness/observe.py + checkers.check_provenance, PR 9):
provenance-on == provenance-off state bit-exactness for all three
sims (stepwise vs donated fused, single-device and 8-way mesh, the
broadcast per-edge ``delays`` ring included), the checker certified
against real certified crash+loss+dup runs AND proven falsifiable
(a forged parent on a dropped/dead edge, a causality-violating
arrival, a tree-inconsistent msgs ledger — each fails loudly),
dissemination-tree / hop-latency summaries, Perfetto flow events
validated against the ONE shared timeline golden for both the
virtual-harness and tpu_sim export paths, the first-divergence
shrinker hook (check_recovery / check_telemetry / replay_bundle),
traffic through the delay-ring broadcast modes, the kafka
``present_bits_full`` opt-in, loud env knobs, and the traced/host
split totality that keeps the PR-6 determinism lint covering the
new module.
"""

import ast as ast_mod
import json
import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

from gossip_glomers_tpu.harness import nemesis as NM
from gossip_glomers_tpu.harness import observe, tracing
from gossip_glomers_tpu.harness.checkers import (
    check_provenance, check_recovery, check_telemetry,
    provenance_divergence_round, series_divergence_round)
from gossip_glomers_tpu.parallel.topology import (to_padded_neighbors,
                                                  tree)
from gossip_glomers_tpu.tpu_sim import audit
from gossip_glomers_tpu.tpu_sim import provenance as PV
from gossip_glomers_tpu.tpu_sim import telemetry as TM
from gossip_glomers_tpu.tpu_sim import traffic as T
from gossip_glomers_tpu.tpu_sim.broadcast import (BroadcastSim,
                                                  make_inject)
from gossip_glomers_tpu.tpu_sim.counter import CounterSim
from gossip_glomers_tpu.tpu_sim.engine import unpack_bits
from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec
from gossip_glomers_tpu.tpu_sim.kafka import KafkaSim


def mesh_1d():
    return Mesh(np.array(jax.devices()).reshape(8), ("nodes",))


def full_spec(n, seed=7):
    """crash + loss + dup — the full fault model."""
    return NemesisSpec(n_nodes=n, seed=seed,
                       crash=((2, 5, (1, n // 2)),),
                       loss_rate=0.15, loss_until=8,
                       dup_rate=0.1, dup_until=8)


def leaves_equal(a, b):
    for x, y in zip(jax.tree_util.tree_leaves(a),
                    jax.tree_util.tree_leaves(b)):
        if not (np.asarray(x) == np.asarray(y)).all():
            return False
    return True


def received_bool(sim, state):
    rec = sim.received_node_major(state)
    v = np.arange(sim.n_values)
    return ((rec[:, v // 32] >> (v % 32).astype(np.uint32)) & 1) \
        .astype(bool)


# -- spec ----------------------------------------------------------------


def test_spec_validation_and_meta_roundtrip():
    spec = PV.ProvenanceSpec("kafka", witness=3)
    assert PV.ProvenanceSpec.from_meta(spec.to_meta()) == spec
    with pytest.raises(ValueError, match="workload"):
        PV.ProvenanceSpec("paxos")
    with pytest.raises(ValueError, match="witness"):
        PV.ProvenanceSpec("kafka", witness=-1)
    with pytest.raises(ValueError, match="together"):
        PV.prov_key(None, PV.ProvenanceSpec("counter"), "counter")
    with pytest.raises(ValueError, match="workload"):
        PV.prov_key(object(), PV.ProvenanceSpec("kafka"), "counter")


def test_unpack_bits_layout():
    words = np.array([[0b101, 0]], np.uint32)
    bits = np.asarray(unpack_bits(words))
    assert bits.shape == (1, 64)
    assert bits[0, 0] and not bits[0, 1] and bits[0, 2]
    assert np.asarray(unpack_bits(words, 3)).shape == (1, 3)


# -- bit-exactness: provenance-on == provenance-off ----------------------


@pytest.mark.parametrize("mesh_on", [False, True])
def test_broadcast_provenance_bit_exact(mesh_on):
    n, nv, rounds = 32, 64, 12
    mesh = mesh_1d() if mesh_on else None
    spec = full_spec(n)
    nbrs = to_padded_neighbors(tree(n, branching=4))
    sim = BroadcastSim(nbrs, n_values=nv, sync_every=4,
                       srv_ledger=False, fault_plan=spec.compile(),
                       mesh=mesh)
    inj = make_inject(n, nv)
    psp = PV.ProvenanceSpec("broadcast")
    s0, _ = sim.stage(inj)
    plain = sim.run_staged_fixed(s0, rounds, donate=True)
    s1, _ = sim.stage(inj)
    obs, prov = sim.run_observed(
        s1, None, None, rounds, donate=True,
        prov=sim.provenance_state(psp, inj), prov_spec=psp)
    assert leaves_equal(plain, obs)
    # stepwise (1-round programs) records the identical stamps
    s2, _ = sim.stage(inj)
    p2 = sim.provenance_state(psp, inj)
    for _ in range(rounds):
        s2, p2 = sim.run_observed(s2, None, None, 1, prov=p2,
                                  prov_spec=psp)
    assert leaves_equal(s2, obs) and leaves_equal(p2, prov)
    # the record certifies against the fault model itself
    ok, det = check_provenance(
        "broadcast", PV.arrays_of(prov), spec=spec, nbrs=nbrs,
        received=received_bool(sim, obs), msgs_total=int(obs.msgs))
    assert ok, det["problems"]
    assert det["n_origins"] == nv
    # and composes with telemetry in the same carry
    tsp = TM.TelemetrySpec("broadcast", rounds=rounds)
    s3, _ = sim.stage(inj)
    obs3, tel3, prov3 = sim.run_observed(
        s3, sim.telemetry_state(tsp), tsp, rounds, donate=True,
        prov=sim.provenance_state(psp, inj), prov_spec=psp)
    assert leaves_equal(plain, obs3) and leaves_equal(prov, prov3)
    assert TM.series_arrays(tel3, tsp)["msgs"][-1] == int(obs3.msgs)


@pytest.mark.parametrize("mesh_on", [False, True])
def test_broadcast_delays_provenance_bit_exact(mesh_on):
    """The per-edge ``delays`` ring path: stamps record the edge's
    latency class (arrival - send = delay(edge)) and the checker
    re-evaluates the coins at the SEND round."""
    n, nv, rounds = 32, 64, 16
    mesh = mesh_1d() if mesh_on else None
    nbrs = to_padded_neighbors(tree(n, branching=4))
    rng = np.random.default_rng(0)
    delays = np.where(np.asarray(nbrs) >= 0,
                      rng.integers(1, 4, nbrs.shape), 1) \
        .astype(np.int32)
    spec = NemesisSpec(n_nodes=n, seed=5, crash=((3, 6, (2,)),),
                       loss_rate=0.1, loss_until=8)
    sim = BroadcastSim(nbrs, n_values=nv, sync_every=4,
                       srv_ledger=False, delays=delays,
                       fault_plan=spec.compile(), mesh=mesh)
    inj = make_inject(n, nv)
    psp = PV.ProvenanceSpec("broadcast")
    s0, _ = sim.stage(inj)
    plain = sim.run_staged_fixed(s0, rounds, donate=True)
    s1, _ = sim.stage(inj)
    obs, prov = sim.run_observed(
        s1, None, None, rounds, donate=True,
        prov=sim.provenance_state(psp, inj), prov_spec=psp)
    assert leaves_equal(plain, obs)
    ok, det = check_provenance(
        "broadcast", PV.arrays_of(prov), spec=spec, nbrs=nbrs,
        received=received_bool(sim, obs), msgs_total=int(obs.msgs),
        delays=delays)
    assert ok, det["problems"]
    assert det["n_tree_edges"] > 0


@pytest.mark.parametrize("mesh_on", [False, True])
def test_counter_provenance_bit_exact(mesh_on):
    n, rounds = 16, 16
    mesh = mesh_1d() if mesh_on else None
    spec = full_spec(n)
    sim = CounterSim(n, mode="cas", poll_every=2,
                     fault_plan=spec.compile(), mesh=mesh)
    deltas = np.arange(1, n + 1, dtype=np.int32)
    plain = sim.run_fused(sim.add(sim.init_state(), deltas), rounds)
    psp = PV.ProvenanceSpec("counter")
    obs, prov = sim.run_observed(
        sim.add(sim.init_state(), deltas), None, None, rounds,
        donate=True, prov=sim.provenance_state(psp), prov_spec=psp)
    assert leaves_equal(plain, obs)
    s2 = sim.add(sim.init_state(), deltas)
    p2 = sim.provenance_state(psp)
    for _ in range(rounds):
        s2, p2 = sim.run_observed(s2, None, None, 1, prov=p2,
                                  prov_spec=psp)
    assert leaves_equal(s2, obs) and leaves_equal(p2, prov)
    ok, det = check_provenance("counter", PV.arrays_of(prov),
                               spec=spec,
                               final_kv=int(sim.kv_value(obs)))
    assert ok, det["problems"]
    assert det["n_flushed"] > 0
    # visibility never precedes the flush (stamp semantics)
    arrs = PV.arrays_of(prov)
    vis = arrs["visible_round"]
    assert (vis[vis >= 0] >= arrs["flush_round"][vis >= 0]).all()


@pytest.mark.parametrize("mesh_on", [False, True])
def test_kafka_provenance_bit_exact(mesh_on):
    n, k, rounds = 16, 4, 12
    mesh = mesh_1d() if mesh_on else None
    spec = full_spec(n)
    sks, svs, crs = NM.stage_kafka_ops(spec, rounds, n_keys=k,
                                       max_sends=2, workload_seed=0)
    sim = KafkaSim(n, k, capacity=64, max_sends=2,
                   fault_plan=spec.compile(), resync_every=4,
                   mesh=mesh)
    plain = sim.run_fused(sim.init_state(), sks, svs, crs)
    psp = PV.ProvenanceSpec("kafka")
    obs, prov = sim.run_observed(
        sim.init_state(), None, None, sks, svs, crs, donate=True,
        prov=sim.provenance_state(psp), prov_spec=psp)
    assert leaves_equal(plain, obs)
    ok, det = check_provenance(
        "kafka", PV.arrays_of(prov), spec=spec, n_nodes=n,
        resync_every=4, resync_mode="pull", witness=0)
    assert ok, det["problems"]
    assert det["n_allocated"] == int(
        (np.asarray(obs.log_vals) >= 0).sum())
    # the alloc stamps mirror the round's own allocator: every
    # allocated slot has a round + origin, unallocated have neither
    arrs = PV.arrays_of(prov)
    allocated = np.asarray(obs.log_vals) >= 0
    assert ((arrs["alloc_round"] >= 1) == allocated).all()
    assert ((arrs["origin"] >= 0) == allocated).all()


# -- falsifiability (the acceptance negatives) ---------------------------


def _certified_broadcast():
    n, nv = 16, 32
    spec = NemesisSpec(n_nodes=n, seed=3, crash=((2, 5, (1,)),),
                       loss_rate=0.2, loss_until=8)
    nbrs = to_padded_neighbors(tree(n, branching=4))
    sim = BroadcastSim(nbrs, n_values=nv, sync_every=4,
                       srv_ledger=False, fault_plan=spec.compile())
    inj = make_inject(n, nv)
    psp = PV.ProvenanceSpec("broadcast")
    s, _ = sim.stage(inj)
    s, prov = sim.run_observed(
        s, None, None, 16, donate=True,
        prov=sim.provenance_state(psp, inj), prov_spec=psp)
    arrs = {k: v.copy() for k, v in PV.arrays_of(prov).items()}
    ctx = dict(spec=spec, nbrs=nbrs, received=received_bool(sim, s),
               msgs_total=int(s.msgs))
    ok, det = check_provenance("broadcast", arrs, **ctx)
    assert ok, det["problems"]
    return arrs, ctx


def test_forged_parent_on_dead_edge_fails():
    """A parent claim on an edge whose endpoint was DOWN at the send
    round must fail — the host re-evaluates the liveness columns.
    Line 0-1-2, node 1 down rounds [2, 20): node 2 claiming a round-4
    delivery from node 1 is a forged parent on a dead edge."""
    spec = NemesisSpec(n_nodes=3, seed=1, crash=((2, 20, (1,)),))
    nbrs = np.array([[1, -1], [0, 2], [1, -1]], np.int32)
    arrs = {"arrival": np.array([[0], [2], [5]], np.int32),
            "parent": np.array([[-1], [0], [1]], np.int32)}
    ok, det = check_provenance(
        "broadcast", arrs, spec=spec, nbrs=nbrs,
        received=np.ones((3, 1), bool), msgs_total=100)
    assert not ok
    assert any("dead or dropped" in p for p in det["problems"])
    # the same claim BEFORE the crash window is legitimate
    arrs["arrival"][2, 0] = 3      # send round 2?  no: round 2 down
    arrs["arrival"][2, 0] = 2 + 1  # delivered by send round 2 — down
    ok2, _ = check_provenance(
        "broadcast", arrs, spec=spec, nbrs=nbrs,
        received=np.ones((3, 1), bool), msgs_total=100)
    assert not ok2
    arrs2 = {"arrival": np.array([[0], [1], [2]], np.int32),
             "parent": np.array([[-1], [0], [1]], np.int32)}
    ok3, det3 = check_provenance(
        "broadcast", arrs2, spec=spec, nbrs=nbrs,
        received=np.ones((3, 1), bool), msgs_total=100)
    assert ok3, det3["problems"]


def test_forged_parent_on_dropped_edge_fails():
    """A parent claim on an edge whose loss coin DROPPED the delivery
    must fail — the coins are stateless (t, src, dst) hashes the host
    re-evaluates exactly."""
    n, nv = 2, 1
    spec = NemesisSpec(n_nodes=n, seed=1, loss_rate=1.0,
                       loss_until=100)
    nbrs = np.array([[1], [0]], np.int32)
    # value 0 injected at node 0 only; every delivery coin drops, so
    # node 1 never legitimately receives it
    arrs = {"arrival": np.array([[0], [3]], np.int32),
            "parent": np.array([[-1], [0]], np.int32)}
    ok, det = check_provenance(
        "broadcast", arrs, spec=spec, nbrs=nbrs,
        received=np.array([[True], [True]]), msgs_total=100)
    assert not ok
    assert any("dropped" in p for p in det["problems"])


def test_causality_violating_arrival_fails():
    arrs, ctx = _certified_broadcast()
    ii, vv = np.nonzero((arrs["arrival"] > 0) & (arrs["parent"] >= 0))
    i, v = ii[0], vv[0]
    p = arrs["parent"][i, v]
    # the parent now claims to have learned the value AFTER the child
    arrs["arrival"][p, v] = arrs["arrival"][i, v] + 1
    ok, det = check_provenance("broadcast", arrs, **ctx)
    assert not ok
    assert any("causality" in p_ for p_ in det["problems"])


def test_tree_inconsistent_msgs_ledger_fails():
    arrs, ctx = _certified_broadcast()
    ctx["msgs_total"] = 3        # < the tree's first-delivery edges
    ok, det = check_provenance("broadcast", arrs, **ctx)
    assert not ok
    assert any("msgs" in p and "ledger" in p
               for p in det["problems"])
    # reachability: a held bit with no recorded arrival
    arrs2, ctx2 = _certified_broadcast()
    i = int(np.argmax(arrs2["arrival"].max(axis=1)))
    v = int(np.argmax(arrs2["arrival"][i]))
    arrs2["arrival"][i, v] = -1
    arrs2["parent"][i, v] = -1
    ok2, det2 = check_provenance("broadcast", arrs2, **ctx2)
    assert not ok2
    assert any("no recorded arrival" in p for p in det2["problems"])


def test_counter_forged_flush_fails():
    n = 16
    spec = NemesisSpec(n_nodes=n, seed=3, crash=((2, 6, (1,)),))
    arrs = {"flush_round": np.full(n, -1, np.int32),
            "flush_kv": np.full(n, -1, np.int32),
            "visible_round": np.full(n, -1, np.int32)}
    # node 1 claims a flush at round 4 — inside its crash window
    arrs["flush_round"][1] = 4
    arrs["flush_kv"][1] = 2
    ok, det = check_provenance("counter", arrs, spec=spec,
                               final_kv=10)
    assert not ok
    assert any("forged flush" in p for p in det["problems"])
    # a flush into a value the monotone KV never passed
    arrs["flush_round"][1] = 10
    arrs["flush_kv"][1] = 99
    ok, det = check_provenance("counter", arrs, spec=spec,
                               final_kv=10)
    assert not ok and any("monotone" in p for p in det["problems"])


def test_kafka_forged_stamps_fail():
    n, k, cap = 8, 2, 8
    spec = NemesisSpec(n_nodes=n, seed=3, crash=((2, 6, (1,)),))
    base = {f: np.full((k, cap), -1, np.int32)
            for f in ("alloc_round", "origin", "first_present")}

    def forged(**cells):
        arrs = {f: a.copy() for f, a in base.items()}
        for f, (kk, cc, val) in cells.items():
            arrs[f][kk, cc] = val
        return check_provenance(
            "kafka", arrs, spec=spec, n_nodes=n, resync_every=4,
            resync_mode="pull", witness=0)

    # allocation claimed by a node that was down at the send round
    ok, det = forged(alloc_round=(0, 0, 4), origin=(0, 0, 1),
                     first_present=(0, 0, 4))
    assert not ok and any("forged allocation" in p
                          for p in det["problems"])
    # witness presence BEFORE allocation
    ok, det = forged(alloc_round=(0, 0, 7), origin=(0, 0, 2),
                     first_present=(0, 0, 3))
    assert not ok and any("BEFORE its allocation" in p
                          for p in det["problems"])
    # a late presence at a non-resync round
    ok, det = forged(alloc_round=(0, 0, 7), origin=(0, 0, 2),
                     first_present=(0, 0, 10))
    assert not ok and any("not a resync round" in p
                          for p in det["problems"])


# -- dissemination trees + timelines (the shared golden) -----------------


def _golden():
    path = os.path.join(os.path.dirname(__file__), "data",
                        "timeline_golden.json")
    return json.load(open(path))


def _validate_against_golden(tl, golden, *, require_flows):
    observe.validate_timeline(tl)
    assert tl["schema"] == golden["schema"]
    assert tl["displayTimeUnit"] == golden["displayTimeUnit"]
    for key in golden["required_top"]:
        assert key in tl, key
    seen = {e["ph"] for e in tl["traceEvents"]}
    required = set(golden["required_phases"])
    if not require_flows:
        required -= {"s", "f"}
    assert required <= seen, (required, seen)
    for ev in tl["traceEvents"]:
        fields = golden["phase_fields"].get(ev["ph"])
        if fields is None:
            continue
        for f in fields:
            if f == "args" and ev["ph"] == "M":
                pass
            assert f in ev, (ev["ph"], f, ev)


def test_timeline_golden_parity_both_paths():
    """Satellite: ONE shared golden validates the Perfetto export of
    BOTH backends — a tpu_sim provenance-on nemesis run (flow events
    from the dissemination trees) and a virtual-harness trace (flow
    events per routed message)."""
    golden = _golden()
    spec = NemesisSpec(n_nodes=16, seed=5, crash=((2, 5, (1, 8)),),
                       loss_rate=0.15, loss_until=8)
    res = NM.run_broadcast_nemesis(spec, provenance=True,
                                   telemetry=True)
    assert res["ok"], res.get("provenance", {}).get("check")
    tl = observe.run_timeline(res)
    _validate_against_golden(tl, golden, require_flows=True)
    flows = [e for e in tl["traceEvents"] if e["ph"] == "s"]
    assert flows and all(e["cat"] == "flow" for e in flows)

    from gossip_glomers_tpu.protocol import Message
    trace = [(0.001, Message("n0", "n1", {"type": "broadcast"})),
             (0.002, Message("n1", "n2", {"type": "broadcast"})),
             (0.003, Message("n2", "n1", {"type": "broadcast_ok"}))]
    tl_v = tracing.to_timeline(trace)
    _validate_against_golden(tl_v, golden, require_flows=True)
    # same arrow count as messages
    assert len([e for e in tl_v["traceEvents"]
                if e["ph"] == "s"]) == 3


def test_dissemination_tree_summary():
    spec = NemesisSpec(n_nodes=16, seed=5, crash=((2, 5, (1, 8)),),
                       loss_rate=0.15, loss_until=8)
    res = NM.run_broadcast_nemesis(spec, provenance=True)
    assert res["ok"]
    d = res["provenance"]["tree"]
    observe.validate_tree(d)
    assert d["n_tree_edges"] == res["provenance"]["check"][
        "n_tree_edges"]
    # hop latency: every per-value span bounds its depth
    for row in d["values"]:
        assert row["span_rounds"] >= row["depth_hops"] >= 0
        assert row["n_reached"] >= 1
    cp = d["critical_path"]
    assert cp["span_rounds"] == d["max_span_rounds"]
    assert cp["chain"][0]["round"] == 0          # rooted at an origin
    assert cp["chain"][-1]["round"] == cp["span_rounds"]
    assert d["edges"] and all(e["n_values"] >= 1 for e in d["edges"])
    # the tree artifact is JSON-able as committed
    json.dumps(d)


def test_validate_timeline_rejects_acausal_flow():
    tb = observe.TimelineBuilder("bad")
    tb.slice("a", "x", 0.0, 1.0)
    tb.flow("v", "a", 5.0, "a", 1.0)     # finishes before it starts
    with pytest.raises(ValueError, match="causality"):
        observe.validate_timeline(tb.to_dict())
    tb2 = observe.TimelineBuilder("bad2")
    tb2.events.append({"ph": "s", "pid": 1, "tid": 1, "id": 9,
                       "name": "v", "ts": 0.0})
    with pytest.raises(ValueError, match="pair"):
        observe.validate_timeline(tb2.to_dict())


# -- the first-divergence shrinker hook ----------------------------------


def test_divergence_rounds_and_checker_hooks():
    exp = {"_round": [0, 1, 2], "msgs": [4, 8, 12],
           "live_nodes": [8, 8, 8]}
    assert series_divergence_round(exp, exp) is None
    got = {"_round": [0, 1, 2], "msgs": [4, 8, 13],
           "live_nodes": [8, 8, 8]}
    assert series_divergence_round(exp, got) == 2
    # check_telemetry surfaces it loudly under expected=
    ok, det = check_telemetry(got, expected=exp)
    assert not ok and det["first_divergence_round"] == 2
    assert any("diverge" in p for p in det["problems"])
    ok, det = check_telemetry(exp, expected=exp)
    assert ok and det["first_divergence_round"] is None
    # provenance stamps: earliest differing stamp round wins
    a = {"arrival": np.array([[0, 3], [2, -1]], np.int32)}
    b = {"arrival": np.array([[0, 3], [2, -1]], np.int32)}
    assert provenance_divergence_round(a, b) is None
    b["arrival"][1, 0] = 5
    assert provenance_divergence_round(a, b) == 2
    assert provenance_divergence_round(
        a, {"arrival": np.zeros((3, 3), np.int32)}) == 0
    # check_recovery passes the divergence through to details
    ok, det = check_recovery(clear_round=4, converged_round=6,
                             max_recovery_rounds=8, lost_writes=[],
                             divergence=3)
    assert det["first_divergence_round"] == 3


def test_flight_bundle_replay_reports_first_divergence(tmp_path):
    """A certified crash+loss campaign forced to fail (impossible
    recovery budget) bundles its provenance + series; the replay is
    deterministic, so the reported first-divergence round is None —
    and a TAMPERED record fires at the tampered round (the negative
    proof the shrinker hook works)."""
    spec = NemesisSpec(n_nodes=8, seed=3, crash=((2, 6, (1, 5)),),
                       loss_rate=0.2, loss_until=8)
    bad = NM.run_kafka_nemesis(spec, telemetry=True, provenance=True,
                               observe_dir=str(tmp_path),
                               max_recovery_rounds=0)
    assert not bad["ok"] and "flight_bundle" in bad
    bundle = observe.load_bundle(bad["flight_bundle"])
    assert bundle["provenance_spec"]["workload"] == "kafka"
    assert bundle["provenance"]["alloc_round"]
    replay = observe.replay_bundle(bad["flight_bundle"])
    assert not replay["ok"]
    assert replay["first_divergence_round"] is None
    assert replay["converged_round"] == bad["converged_round"]
    # tamper the recorded provenance: the replay must report the
    # forged round as the first divergence
    forged = {k: [r[:] for r in v]
              for k, v in bundle["provenance"].items()}
    rounds = [r for row in forged["alloc_round"] for r in row
              if r >= 1]
    target = max(rounds)
    done = False
    for row in forged["alloc_round"]:
        for i, r in enumerate(row):
            if r == target and not done:
                row[i] = r + 7
                done = True
    tampered = dict(bundle, provenance=forged)
    replay2 = observe.replay_bundle(tampered)
    assert replay2["first_divergence_round"] == target
    # tampered telemetry fires too, at the earlier of the two
    t_series = {k: (v[:] if isinstance(v, list) else v)
                for k, v in bundle["telemetry_series"].items()}
    t_series["msgs"] = list(t_series["msgs"])
    t_series["msgs"][0] += 1
    first_round = t_series["_round"][0]
    replay3 = observe.replay_bundle(
        dict(bundle, telemetry_series=t_series))
    assert replay3["first_divergence_round"] == first_round


# -- runner integration + env knobs --------------------------------------


def test_env_switch_drives_runners(monkeypatch):
    spec = NemesisSpec(n_nodes=8, seed=3, crash=((12, 16, (1,)),))
    monkeypatch.setenv("GG_PROVENANCE", "1")
    res = NM.run_counter_nemesis(spec)
    assert res["ok"] and "provenance" in res
    assert res["provenance"]["check"]["n_flushed"] > 0
    monkeypatch.delenv("GG_PROVENANCE")
    res_off = NM.run_counter_nemesis(spec)
    assert "provenance" not in res_off
    # provenance-on is pinned bit-exact to provenance-off
    assert res_off["converged_round"] == res["converged_round"]
    assert res_off["msgs_total"] == res["msgs_total"]


def test_env_knob_is_loud(monkeypatch):
    monkeypatch.setenv("GG_PROVENANCE", "yes")
    with pytest.raises(ValueError, match="GG_PROVENANCE"):
        PV.enabled()
    monkeypatch.setenv("GG_PROVENANCE", "2")
    with pytest.raises(ValueError, match="GG_PROVENANCE"):
        PV.enabled()
    monkeypatch.setenv("GG_PROVENANCE", "1")
    assert PV.enabled() is True
    monkeypatch.delenv("GG_PROVENANCE")
    assert PV.enabled() is False


def test_runner_rejections_are_loud():
    spec = NemesisSpec(n_nodes=8, seed=3, crash=((2, 4, (1,)),))
    tspec = T.TrafficSpec(n_nodes=8, n_clients=8, ops_per_client=2,
                          until=4, rate=0.5, seed=1)
    with pytest.raises(ValueError, match="traffic"):
        NM.run_counter_nemesis(spec, traffic=tspec, provenance=True)
    with pytest.raises(ValueError, match="gather"):
        NM.run_broadcast_nemesis(spec, structured=True,
                                 provenance=True)
    nbrs = to_padded_neighbors(tree(8, branching=4))
    from gossip_glomers_tpu.tpu_sim import structured as S
    sim = BroadcastSim(nbrs, n_values=16,
                       exchange=S.make_exchange("tree", 8,
                                                branching=4))
    psp = PV.ProvenanceSpec("broadcast")
    with pytest.raises(ValueError, match="words-major|gather"):
        sim.run_observed(
            sim.init_state(np.zeros((8, 1), np.uint32)), None, None,
            2, prov=sim.provenance_state(psp, np.zeros((8, 1),
                                                       np.uint32)),
            prov_spec=psp)


# -- traffic through the delay-ring modes (satellite) --------------------


@pytest.mark.parametrize("mesh_on", [False, True])
def test_traffic_through_delay_ring_modes(mesh_on):
    """The ROADMAP item-1 leftover: broadcast's per-edge ``delays``
    gather mode takes open-loop traffic — ops flood with the edge
    latency and the loud backpressure identity holds."""
    n, nv = 32, 256
    mesh = mesh_1d() if mesh_on else None
    nbrs = to_padded_neighbors(tree(n, branching=4))
    rng = np.random.default_rng(0)
    delays = np.where(np.asarray(nbrs) >= 0,
                      rng.integers(1, 4, nbrs.shape), 1) \
        .astype(np.int32)
    spec = NemesisSpec(n_nodes=n, seed=5, crash=((3, 6, (2,)),),
                       loss_rate=0.1, loss_until=8)
    tspec = T.TrafficSpec(n_nodes=n, n_clients=8, ops_per_client=6,
                          until=12, rate=0.4, seed=1)
    sim = BroadcastSim(nbrs, n_values=nv, sync_every=4,
                       srv_ledger=False, delays=delays,
                       fault_plan=spec.compile(), mesh=mesh)
    st, ts = sim.run_traffic(
        sim.init_state(np.zeros((n, nv // 32), np.uint32)),
        sim.traffic_state(tspec), tspec, 30, donate=True)
    issued = int((np.asarray(ts.issue_round) >= 0).sum())
    assert int(ts.arrived) == issued + int(ts.deferred)
    assert int(ts.completed) == issued       # all drained
    assert int(ts.completed) > 0
    # delayed completion: with min edge delay 1 and diameter > 1, an
    # op cannot complete in under 2 rounds
    lat = T.latency_summary(ts)
    assert lat["lat_p50"] >= 2


# -- kafka present_bits_full opt-in (satellite) --------------------------


def test_present_bits_full_is_opt_in():
    # the default spec records the witness gauge, NOT the full scan
    dsp = TM.TelemetrySpec("kafka", rounds=8)
    assert "present_bits" in dsp.series
    assert "present_bits_full" not in dsp.series
    # explicit selection still works, and the column records the
    # full-cluster popcount
    full = TM.TelemetrySpec(
        "kafka", rounds=8,
        series=("present_bits", "present_bits_full", "alloc_total"))
    assert "present_bits_full" in full.series
    n, k = 8, 2
    sim = KafkaSim(n, k, capacity=32, max_sends=2)
    sks = np.full((8, n, 2), -1, np.int32)
    sks[:, 0, 0] = 0
    svs = np.zeros((8, n, 2), np.int32)
    plain = sim.run_fused(sim.init_state(), sks, svs, None)
    obs, tel = sim.run_observed(sim.init_state(),
                                sim.telemetry_state(full), full,
                                sks, svs, None, donate=True)
    assert leaves_equal(plain, obs)
    arrs = TM.series_arrays(tel, full)
    pres = np.asarray(obs.present)
    total = int(np.unpackbits(pres.view(np.uint8)).sum())
    assert arrs["present_bits_full"][-1] == total
    # full-presence == N x witness once replication has caught up
    assert arrs["present_bits_full"][-1] == n * arrs[
        "present_bits"][-1]
    # the default spec leaves the opt-in column zeroed in the ring
    obs2, tel2 = sim.run_observed(sim.init_state(),
                                  sim.telemetry_state(dsp), dsp,
                                  sks, svs, None, donate=True)
    ring = np.asarray(tel2.ring)
    col = dsp.names.index("present_bits_full")
    assert (ring[:, col] == 0).all()


# -- lint split + registry ----------------------------------------------


def test_provenance_traced_host_split_is_total():
    import gossip_glomers_tpu
    pkg = os.path.dirname(os.path.abspath(
        gossip_glomers_tpu.__file__))
    src = open(os.path.join(pkg, "tpu_sim", "provenance.py")).read()
    tree_ = ast_mod.parse(src)
    top_fns = {n.name for n in tree_.body
               if isinstance(n, ast_mod.FunctionDef)}
    declared = set(PV.TRACED_EVALUATORS) | set(PV.HOST_SIDE)
    assert top_fns == declared, (
        f"undeclared: {sorted(top_fns - declared)}, "
        f"stale: {sorted(declared - top_fns)}")
    pat = audit._root_pattern_for("tpu_sim/provenance.py")
    for name in PV.TRACED_EVALUATORS:
        assert pat.match(name), name
    for name in PV.HOST_SIDE:
        assert not pat.match(name), name
    # the sims' provenance recorders are traced roots too
    assert audit._root_pattern_for(
        "tpu_sim/broadcast.py").match("_prov_attribute")
    assert audit._root_pattern_for(
        "tpu_sim/counter.py").match("_prov_record")
    assert audit._root_pattern_for(
        "tpu_sim/kafka.py").match("_prov_record")


def test_provenance_contracts_registered():
    rows = {c.name: c for c in audit.default_registry()}
    for expected in ("counter/provenance-run",
                     "broadcast/provenance-run-gather-nem",
                     "kafka/provenance-run-union-nem"):
        assert expected in rows
        c = rows[expected]
        assert c.donation
        # cap-0 all-gather census for counter/kafka; the broadcast
        # gather row pins EXACTLY its plain two widens
        if "broadcast" in expected:
            assert c.collectives["all-gather"] == 2
        else:
            assert "all-gather" not in c.collectives
