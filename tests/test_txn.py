"""txn-rw-register workload (tpu_sim/txn.py, PR 14): wound-or-die
batched transactions over the sharded device KV, the on-device
serializability record (per-op version/value stamps + commit-round
provenance), and the host checker's FALSIFIABILITY — every anomaly
class (lost update, G1a, G1b, write cycle, round-order violation,
lost acked commit) is planted into a hand-crafted history and must
fail loudly naming the offending transaction ids.  Driver parity
(step vs run vs run_fused, single device vs the 8-way virtual mesh),
the nemesis runner's two-sided certification (crash+loss certifies
clean; ``kv_amnesia`` owner wipes MUST fail with named lost updates
and a replayable flight bundle), the scenario-axis batch (64 fuzzed
crash+loss campaigns certified in ONE dispatch — the acceptance
criterion), the fuzz/frontier smokes, the zero-all-gather audit
contract, and the declared traced/host splits' totality.
"""

import ast as ast_mod
import os

import jax
import numpy as np
import pytest
from jax.sharding import Mesh

import gossip_glomers_tpu
from gossip_glomers_tpu.harness import fuzz as FZ
from gossip_glomers_tpu.harness import observe
from gossip_glomers_tpu.harness import txn as HTX
from gossip_glomers_tpu.harness.checkers import check_txn_serializable
from gossip_glomers_tpu.tpu_sim import audit, faults
from gossip_glomers_tpu.tpu_sim import kvstore as KV
from gossip_glomers_tpu.tpu_sim import scenario as SC
from gossip_glomers_tpu.tpu_sim import txn as TX

PKG_DIR = os.path.dirname(gossip_glomers_tpu.__file__)


def mesh_8() -> Mesh:
    return Mesh(np.array(jax.devices()).reshape(8), ("nodes",))


def _trees_equal(a, b) -> bool:
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(jax.tree_util.tree_leaves(a),
                               jax.tree_util.tree_leaves(b)))


# -- clean runs + driver parity ------------------------------------------


def test_clean_run_commits_all_and_serializes():
    n, t_dim = 8, 4
    sim = TX.TxnSim(n, 8, txns_per_node=t_dim, rate=0.5, until=12,
                    workload_seed=11)
    st = sim.init_state()
    for _ in range(40):
        st = sim.step(st)
        if bool(np.all(np.asarray(st.cur) >= np.asarray(st.arrived))) \
                and int(st.t) >= 12:
            break
    hist = TX.history_of(st, sim.ops)
    final = TX.final_registers(st, sim.layout)
    ok, det = check_txn_serializable(hist, final=final)
    assert ok, det["problems"]
    assert det["by_kind"] == {}
    committed = [h for h in hist if h["status"] == "committed"]
    assert det["n_committed"] == len(committed) == len(hist)
    # provenance stamps: every committed txn carries a round pair
    # with commit >= issue (wound-or-die retries only move commit up)
    for h in committed:
        assert 0 <= h["issue_round"] <= h["commit_round"]
    # the store's registers really are the max committed versions
    for key, (val, ver) in final.items():
        installs = [op for h in committed for op in h["ops"]
                    if op["kind"] == "w" and op["key"] == key]
        if installs:
            top = max(op["ver"] for op in installs)
            assert ver == top
            assert val in [op["val"] for op in installs
                           if op["ver"] == top]


def test_step_run_fused_and_mesh_all_bit_exact():
    n = 16
    spec = faults.NemesisSpec(n_nodes=n, seed=7,
                              crash=((2, 4, (3,)),),
                              loss_rate=0.2, loss_until=5)
    kw = dict(txns_per_node=4, ops_per_txn=2, rate=0.5, until=10,
              workload_seed=3, fault_plan=spec.compile())
    single = TX.TxnSim(n, 8, **kw)
    meshed = TX.TxnSim(n, 8, mesh=mesh_8(), **kw)
    sa, sb = single.init_state(), meshed.init_state()
    for _ in range(14):
        sa, sb = single.step(sa), meshed.step(sb)
        assert _trees_equal(sa, sb), int(sa.t)
    assert _trees_equal(single.run(single.init_state(), 14), sa)
    assert _trees_equal(single.run_fused(single.init_state(), 14), sa)


# -- nemesis runner: certify clean, fail loudly under kv_amnesia ---------


def test_nemesis_certifies_crash_loss_campaign():
    spec = faults.NemesisSpec(n_nodes=8, seed=3,
                              crash=((3, 6, (4,)),),
                              loss_rate=0.2, loss_until=6)
    res = HTX.run_txn_nemesis(spec, n_keys=8, until=12,
                              max_recovery_rounds=48)
    assert res["ok"] and res["serializable"]
    assert res["serializability"]["by_kind"] == {}
    assert res["n_lost_writes"] == 0
    assert res["converged_round"] is not None
    assert res["provenance"]["check"]["ok"]
    # stamps rode the state: one (issue, commit) pair per slot
    arr = res["provenance"]["arrays"]
    assert np.asarray(arr["issue_round"]).shape == (8, 4)
    assert np.asarray(arr["commit_round"]).shape == (8, 4)


def test_kv_amnesia_fails_loudly_with_named_lost_updates(tmp_path):
    # node 4 owns keys {0, 2, 6} under the default layout seed — its
    # crash with kv_amnesia wipes acked registers, so later commits
    # re-install already-acked versions: the planted lost update
    n, n_keys = 8, 8
    owners = KV.host_owner_of(np.arange(n_keys, dtype=np.int32), n, 0)
    own = int(owners[0])
    spec = faults.NemesisSpec(n_nodes=n, seed=3,
                              crash=((3, 6, (own,)),))
    res = HTX.run_txn_nemesis(spec, n_keys=n_keys, until=12,
                              max_recovery_rounds=48,
                              kv_amnesia=True,
                              observe_dir=str(tmp_path))
    assert not res["ok"] and not res["serializable"]
    lost = [p for p in res["serializability"]["problems"]
            if p["kind"] in ("lost-update", "lost-acked-commit")]
    assert lost
    for p in lost:
        assert p["txns"], p           # every verdict names txn ids
    # the identical spec WITHOUT owner wipes certifies clean — the
    # failure is the amnesia, not the crash
    durable = HTX.run_txn_nemesis(spec, n_keys=n_keys, until=12,
                                  max_recovery_rounds=48)
    assert durable["ok"] and durable["serializable"]
    # flight bundle: written on failure, replays to the same verdict
    # with bit-faithful per-transaction stamps
    bundle = res["flight_bundle"]
    assert os.path.exists(bundle)
    replay = observe.replay_bundle(bundle)
    assert not replay["ok"]
    assert replay["serializability"]["by_kind"] == \
        res["serializability"]["by_kind"]
    assert replay["first_divergence_round"] is None


def test_nemesis_rejects_telemetry_series():
    spec = faults.NemesisSpec(n_nodes=4, seed=0)
    with pytest.raises(ValueError, match="stamps"):
        HTX.run_txn_nemesis(spec, telemetry=True)


# -- scenario-axis batch -------------------------------------------------


def test_batch_rows_match_sequential_runner():
    n = 8
    specs = [
        faults.NemesisSpec(n_nodes=n, seed=11),
        faults.NemesisSpec(n_nodes=n, seed=5, crash=((2, 5, (1,)),)),
        faults.NemesisSpec(n_nodes=n, seed=9, loss_rate=0.3,
                           loss_until=8),
        faults.NemesisSpec(n_nodes=n, seed=4,
                           crash=((3, 6, (2, 5)),),
                           loss_rate=0.2, loss_until=6),
    ]
    batch = SC.ScenarioBatch(
        workload="txn",
        scenarios=tuple(SC.Scenario(spec=sp, workload_seed=sp.seed)
                        for sp in specs),
        runner_kw=dict(n_keys=8, txns_per_node=4, ops_per_txn=2,
                       rate=0.5, until=12),
        max_recovery_rounds=32)
    res = SC.run_txn_batch(batch)
    assert res["ok"] and len(res["scenarios"]) == 4
    for sp, row in zip(specs, res["scenarios"]):
        seq = HTX.run_txn_nemesis(sp, n_keys=8, until=12,
                                  workload_seed=sp.seed,
                                  max_recovery_rounds=32)
        assert row["ok"] == seq["ok"]
        assert row["converged_round"] == seq["converged_round"]
        assert row["msgs_total"] == seq["msgs_total"]
        assert row["n_committed"] == seq["n_committed"]
        assert row["serializable"] == seq["serializable"]


def test_batch_64_fuzzed_scenarios_certify_in_one_dispatch():
    # THE acceptance criterion: >= 64 fuzzed crash+loss txn campaigns
    # in ONE batched dispatch on the 8-way mesh, every scenario's
    # history serializable with zero lost acked commits
    scs = FZ.sample_scenarios("txn", 64, n_nodes=16, seed=3,
                              horizon=8)
    assert sum(1 for s in scs if s.spec.crash) >= 16
    assert sum(1 for s in scs if s.spec.loss_rate) >= 16
    batch = SC.ScenarioBatch(
        workload="txn", scenarios=tuple(scs),
        runner_kw=dict(n_keys=8, txns_per_node=4, ops_per_txn=2,
                       rate=0.5, until=16),
        max_recovery_rounds=48)
    res = SC.run_txn_batch(batch, mesh=mesh_8())
    assert res["ok"], res["failing"]
    assert len(res["scenarios"]) == 64
    for row in res["scenarios"]:
        assert row["serializable"]
        assert row["ser_by_kind"] == {}
        assert row["n_lost_writes"] == 0
    assert sum(r["n_committed"] for r in res["scenarios"]) > 0


def test_batch_rejects_dup_scenarios_loudly():
    dup = faults.NemesisSpec(n_nodes=8, seed=0, dup_rate=0.2,
                             dup_until=4)
    batch = SC.ScenarioBatch(
        workload="txn", scenarios=(SC.Scenario(spec=dup),),
        runner_kw=dict(until=8))
    with pytest.raises(ValueError, match="dup"):
        SC.run_txn_batch(batch)


# -- checker falsifiability (one planted history per anomaly) ------------


def _txn(tid, ops, *, status="committed", commit=1, issue=0):
    return {"id": tid, "node": 0, "slot": tid, "status": status,
            "issue_round": issue, "commit_round": commit,
            "ops": [{"kind": k, "key": key, "ver": ver, "val": val}
                    for k, key, ver, val in ops]}


def test_checker_passes_a_clean_history():
    hist = [
        _txn(1, [("w", 0, 1, 5)], commit=1),
        _txn(2, [("r", 0, 1, 5), ("w", 1, 1, 6)], commit=2),
    ]
    ok, det = check_txn_serializable(
        hist, final={0: (5, 1), 1: (6, 1)})
    assert ok, det["problems"]
    assert det["n_edges"] >= 1


def test_checker_flags_planted_lost_update():
    hist = [
        _txn(1, [("w", 0, 1, 5)], commit=1),
        _txn(7, [("w", 0, 1, 9)], commit=3),
    ]
    ok, det = check_txn_serializable(hist)
    assert not ok
    [p] = [p for p in det["problems"] if p["kind"] == "lost-update"]
    assert p["txns"] == [1, 7] and p["key"] == 0 and p["ver"] == 1


def test_checker_flags_planted_g1a_aborted_read():
    hist = [
        _txn(3, [("w", 0, 1, 42)], status="open", commit=-1),
        _txn(8, [("r", 0, 1, 42)], commit=2),
    ]
    ok, det = check_txn_serializable(hist)
    assert not ok
    [p] = [p for p in det["problems"]
           if p["kind"] == "G1a-aborted-read"]
    assert p["txns"] == [3, 8] and p["val"] == 42


def test_checker_flags_planted_g1b_intermediate_read():
    hist = [
        _txn(1, [("w", 0, 1, 7)], commit=1),
        _txn(2, [("r", 0, 1, 8)], commit=2),
    ]
    ok, det = check_txn_serializable(hist)
    assert not ok
    [p] = [p for p in det["problems"]
           if p["kind"] == "G1b-intermediate-read"]
    assert p["txns"] == [1, 2]
    assert p["saw"] == 8 and p["committed"] == [7]


def test_checker_flags_planted_write_skew_cycle():
    # classic write skew: each reads the OTHER's key at v0, then
    # writes its own — rw edges both ways, a cycle with no lost write
    hist = [
        _txn(1, [("r", 0, 0, 0), ("w", 1, 1, 5)], commit=2),
        _txn(2, [("r", 1, 0, 0), ("w", 0, 1, 6)], commit=2),
    ]
    ok, det = check_txn_serializable(hist)
    assert not ok
    [p] = [p for p in det["problems"] if p["kind"] == "write-cycle"]
    assert p["txns"] == [1, 2]
    assert set(p["cycle"]) == {1, 2}


def test_checker_flags_planted_round_order_violation():
    # a wr dependency running BACKWARD in commit rounds falsifies the
    # linearization claim even before any cycle closes
    hist = [
        _txn(1, [("w", 0, 1, 3)], commit=5),
        _txn(2, [("r", 0, 1, 3)], commit=2),
    ]
    ok, det = check_txn_serializable(hist)
    assert not ok
    [p] = [p for p in det["problems"]
           if p["kind"] == "round-order-violation"]
    assert p["txns"] == [1, 2] and tuple(p["rounds"]) == (5, 2)


def test_checker_flags_planted_lost_acked_commit():
    hist = [_txn(4, [("w", 0, 1, 9)], commit=1)]
    ok, det = check_txn_serializable(hist, final={0: (0, 0)})
    assert not ok
    [p] = [p for p in det["problems"]
           if p["kind"] == "lost-acked-commit"]
    assert p["txns"] == [4]
    assert p["final_ver"] == 0 and p["max_committed_ver"] == 1


def test_checker_flags_dangling_version_read():
    hist = [_txn(6, [("r", 0, 3, 77)], commit=1)]
    ok, det = check_txn_serializable(hist)
    assert not ok
    [p] = [p for p in det["problems"]
           if p["kind"] == "dangling-version-read"]
    assert p["txns"] == [6]


# -- fuzz + frontier smokes ----------------------------------------------


def test_fuzz_run_txn_smoke():
    res = FZ.fuzz_run("txn", 8, n_nodes=8, batch_size=4, horizon=6,
                      max_recovery_rounds=32, seed=7, shrink=False,
                      runner_kw=dict(n_keys=8, until=10))
    assert res["n_failing"] == 0
    assert res["n_certified_ok"] == len(res["rows"]) == 8
    for row in res["rows"]:
        assert row["serializable"]
    # no telemetry ring for this workload: signatures/adapt refuse
    with pytest.raises(ValueError, match="stamps"):
        FZ.fuzz_run("txn", 4, n_nodes=8, batch_size=4, horizon=6,
                    signatures=True)
    with pytest.raises(ValueError, match="planted-failure"):
        FZ.planted_failure("txn", 8, 6)


def test_frontier_txn_smoke_with_slo():
    specs = [faults.NemesisSpec(n_nodes=8, seed=1),
             faults.NemesisSpec(n_nodes=8, seed=2,
                                crash=((2, 4, (1,)),))]
    res = HTX.run_txn_frontier(
        [0.3, 0.8], specs, n_keys=8, until=10,
        max_recovery_rounds=32,
        slo={"p99_max_rounds": 40, "max_recovery_rounds": 32})
    assert res["ok"] and res["n_cells"] == 4
    for cell in res["cells"]:
        assert cell["slo_ok"]
        assert cell["lat_p50"] <= cell["lat_p99"] <= cell["lat_max"]
        assert cell["n_committed"] > 0


# -- audit contract + declared split totality ----------------------------


def test_txn_sharded_step_contract_is_all_reduce_only():
    [contract] = [c for c in TX.audit_contracts()
                  if c.name == "txn/sharded-step"]
    res = audit.audit_contract(contract, mesh_8())
    assert res["ok"], res
    counts = res["checks"]["collectives"]["counts"]
    assert counts.get("all-gather", 0) == 0
    assert counts.get("all-reduce", 0) >= 1


@pytest.mark.parametrize("mod, relpath", [
    (TX, os.path.join("tpu_sim", "txn.py")),
    (HTX, os.path.join("harness", "txn.py")),
])
def test_txn_traced_host_split_is_total(mod, relpath):
    src = open(os.path.join(PKG_DIR, relpath)).read()
    tree = ast_mod.parse(src)
    top_fns = {node.name for node in tree.body
               if isinstance(node, ast_mod.FunctionDef)}
    declared = set(mod.TRACED_EVALUATORS) | set(mod.HOST_SIDE)
    assert top_fns == declared, (
        f"undeclared: {sorted(top_fns - declared)}, "
        f"stale: {sorted(declared - top_fns)}")
    pat = audit._root_pattern_for(relpath.replace(os.sep, "/"))
    for name in mod.TRACED_EVALUATORS:
        assert pat.match(name), name
    for name in mod.HOST_SIDE:
        assert not pat.match(name), name
