"""Cross-implementation parity: our stack vs the reference Go binaries.

The reference repo checks in its compiled Maelstrom node binaries
(broadcast, counter, kafka).  These tests execute them as opaque
artifacts under our in-repo process harness and drive the identical
workload into our own stdio nodes and virtual-clock harness, asserting:

- identical convergence results, and
- identical server-to-server message counts in the deterministic
  eager-flood window (before the reference's first randomized
  anti-entropy timer at 2 s + jitter, broadcast/main.go:45-48).

This is the "bit-identical message counts vs. the Go reference"
criterion of BASELINE.json, made executable without any Maelstrom
install.
"""

import os
import sys

import pytest

from gossip_glomers_tpu.harness.process_net import ProcessNetwork
from gossip_glomers_tpu.parallel.topology import tree, to_name_map

GO_BROADCAST = "/root/reference/broadcast/maelstrom-broadcast"
GO_COUNTER = "/root/reference/counter/maelstrom-grow-only-counter"
GO_KAFKA = "/root/reference/kafka/maelstrom-kafka"

needs_go = pytest.mark.skipif(
    not os.path.exists(GO_BROADCAST),
    reason="reference binaries not mounted")

PY = [sys.executable, "-m"]


def run_broadcast_flood(argv_of, n=5, n_values=8):
    """Spawn n nodes, flood n_values, return (server msgs by type,
    per-node final reads)."""
    net = ProcessNetwork()
    try:
        for i in range(n):
            net.spawn(f"n{i}", argv_of(i))
        net.init_cluster()
        net.set_topology(to_name_map(tree(n)))
        for v in range(n_values):
            rep = net.rpc(f"n{v % n}", {"type": "broadcast", "message": v})
            assert rep["type"] == "broadcast_ok", rep
        net.quiesce(idle=0.3, timeout=5.0)
        msgs = dict(net.server_msgs_by_type)
        reads = {}
        for i in range(n):
            rep = net.rpc(f"n{i}", {"type": "read"})
            reads[f"n{i}"] = sorted(rep.get("messages") or [])
        return msgs, reads
    finally:
        net.shutdown()


def analytic_flood_count(n=5, n_values=8):
    """Eager flood on a tree sends deg(origin) + sum_{i != origin}
    (deg(i)-1) value-messages per value (rebroadcastAllExcept,
    broadcast.go:50-57) — on a tree that is exactly n-1 per value."""
    return n_values * (n - 1)


@needs_go
def test_go_binaries_flood_count_and_convergence():
    msgs, reads = run_broadcast_flood(lambda i: [GO_BROADCAST])
    assert all(r == list(range(8)) for r in reads.values())
    assert msgs["broadcast"] == analytic_flood_count()
    # every server-to-server broadcast is acked (broadcast.go:69,78)
    assert msgs["broadcast_ok"] == msgs["broadcast"]


def test_our_stdio_nodes_match_go_flood_counts():
    msgs, reads = run_broadcast_flood(
        lambda i: PY + ["gossip_glomers_tpu.nodes.broadcast"])
    assert all(r == list(range(8)) for r in reads.values())
    assert msgs["broadcast"] == analytic_flood_count()
    assert msgs["broadcast_ok"] == msgs["broadcast"]


def test_virtual_harness_matches_go_flood_counts():
    from gossip_glomers_tpu.harness.workloads import run_broadcast

    res = run_broadcast(n_nodes=5, topology="tree", n_values=8,
                        rate=100.0, quiescence=0.5, seed=0)
    assert res.ok
    # quiescence kept below the 2 s anti-entropy timer: eager flood only
    by_type = res.stats["by_type"]
    assert res.stats["server_msgs_at_quiescence"] == \
        2 * analytic_flood_count()  # broadcast + broadcast_ok
    assert by_type["broadcast"] - 8 == analytic_flood_count()  # -client ops


def _counter_session(argv):
    """2 nodes + seq-kv: three adds, wait out the 200 ms flush cadence
    and 700 ms read-poll (add.go:62, counter/main.go:53), read both."""
    import time

    net = ProcessNetwork()
    try:
        net.add_kv("seq-kv")
        for i in range(2):
            net.spawn(f"n{i}", list(argv))
        net.init_cluster()
        for d in (3, 4, 5):
            rep = net.rpc(f"n{d % 2}", {"type": "add", "delta": d})
            assert rep["type"] == "add_ok"
        time.sleep(1.6)
        return [net.rpc(f"n{i}", {"type": "read"})["value"]
                for i in range(2)]
    finally:
        net.shutdown()


@needs_go
def test_go_counter_semantics():
    assert _counter_session([GO_COUNTER]) == [12, 12]


def test_our_counter_matches_go_semantics():
    assert _counter_session(
        PY + ["gossip_glomers_tpu.nodes.counter"]) == [12, 12]


def _kafka_session(argv, poll_field, poll_from):
    """The checked-in Go kafka binary predates the checked-in source: it
    replies to poll with field ``offsets`` (and returns nothing for
    from-offset 0), where kafka/log.go:85-88 says ``msgs`` (and returns
    everything >= the requested offset).  The source is the authoritative
    reference; our node follows it.  The session parameterizes over the
    dialect so both stacks are checked for the same content."""
    net = ProcessNetwork()
    try:
        net.add_kv("lin-kv")
        for i in range(2):
            net.spawn(f"n{i}", list(argv))
        net.init_cluster()
        offs = [net.rpc("n0", {"type": "send", "key": "k1",
                               "msg": 100 + j})["offset"]
                for j in range(3)]
        net.quiesce(idle=0.3, timeout=5.0)
        poll0 = net.rpc("n0", {"type": "poll",
                               "offsets": {"k1": poll_from}})[poll_field]
        poll1 = net.rpc("n1", {"type": "poll",
                               "offsets": {"k1": poll_from}})[poll_field]
        net.rpc("n0", {"type": "commit_offsets",
                       "offsets": {"k1": offs[-1]}})
        listed = net.rpc("n0", {"type": "list_committed_offsets",
                                "keys": ["k1"]})["offsets"]
        return offs, poll0, poll1, listed
    finally:
        net.shutdown()


@needs_go
def test_go_kafka_semantics():
    # The artifact is older still than its poll dialect suggests: it
    # allocates offsets locally (no lin-kv traffic) and does not
    # replicate to peers at all — a single-node-stage build (the 5a
    # solution in the challenge progression).  Assert the behavior the
    # artifact actually has; full replicated semantics are asserted
    # against kafka/log.go via our node below.
    offs, poll0, poll1, listed = _kafka_session(
        [GO_KAFKA], poll_field="offsets", poll_from=1)
    assert offs == [1, 2, 3]
    assert poll0["k1"] == [[1, 100], [2, 101], [3, 102]]
    assert poll1["k1"] == []  # no replication in this build
    assert listed == {"k1": 3}


def test_our_kafka_matches_go_semantics():
    # our node speaks the source dialect: "msgs", from-offset 0 = all
    offs, poll0, poll1, listed = _kafka_session(
        PY + ["gossip_glomers_tpu.nodes.kafka"],
        poll_field="msgs", poll_from=0)
    assert offs == [1, 2, 3]
    assert poll0["k1"] == [[1, 100], [2, 101], [3, 102]]
    assert poll1["k1"] == poll0["k1"]
    assert listed == {"k1": 3}
