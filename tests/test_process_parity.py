"""Cross-implementation parity: our stack vs the reference Go binaries.

The reference repo checks in its compiled Maelstrom node binaries
(broadcast, counter, kafka).  These tests execute them as opaque
artifacts under our in-repo process harness and drive the identical
workload into our own stdio nodes and virtual-clock harness, asserting:

- identical convergence results, and
- identical server-to-server message counts in the deterministic
  eager-flood window (before the reference's first randomized
  anti-entropy timer at 2 s + jitter, broadcast/main.go:45-48).

This is the "bit-identical message counts vs. the Go reference"
criterion of BASELINE.json, made executable without any Maelstrom
install.
"""

import json
import os
import sys

import pytest

from gossip_glomers_tpu.harness.process_net import ProcessNetwork
from gossip_glomers_tpu.parallel.topology import tree, to_name_map

GO_BROADCAST = "/root/reference/broadcast/maelstrom-broadcast"
GO_COUNTER = "/root/reference/counter/maelstrom-grow-only-counter"
GO_KAFKA = "/root/reference/kafka/maelstrom-kafka"

needs_go = pytest.mark.skipif(
    not os.path.exists(GO_BROADCAST),
    reason="reference binaries not mounted")

PY = [sys.executable, "-m"]


def run_broadcast_flood(argv_of, n=5, n_values=8, extra_env=None):
    """Spawn n nodes, flood n_values, return (server msgs by type,
    per-node final reads)."""
    from concurrent.futures import ThreadPoolExecutor

    net = ProcessNetwork()
    try:
        with ThreadPoolExecutor(max_workers=min(n, 16)) as pool:
            list(pool.map(lambda i: net.spawn(f"n{i}", argv_of(i),
                                              extra_env=extra_env),
                          range(n)))
        net.init_cluster(timeout=60.0)
        net.set_topology(to_name_map(tree(n)))
        # generous per-op timeouts: under a loaded full-suite run the
        # first ops race 25 interpreter startups; slow is fine, counts
        # are what's asserted
        for v in range(n_values):
            rep = net.rpc(f"n{v % n}", {"type": "broadcast", "message": v},
                          timeout=30.0)
            assert rep["type"] == "broadcast_ok", rep
        net.quiesce(idle=0.3, timeout=15.0)
        msgs = dict(net.server_msgs_by_type)
        reads = {}
        for i in range(n):
            rep = net.rpc(f"n{i}", {"type": "read"}, timeout=30.0)
            reads[f"n{i}"] = sorted(rep.get("messages") or [])
        return msgs, reads
    finally:
        net.shutdown()


def analytic_flood_count(n=5, n_values=8):
    """Eager flood on a tree sends deg(origin) + sum_{i != origin}
    (deg(i)-1) value-messages per value (rebroadcastAllExcept,
    broadcast.go:50-57) — on a tree that is exactly n-1 per value."""
    return n_values * (n - 1)


@needs_go
def test_25_node_flood_parity_go_vs_ours():
    """BASELINE config 1 at full size: 25-node tree, no faults —
    bit-identical server message counts, Go binary vs our stdio nodes.
    (Our nodes' anti-entropy timer is pushed out of the window so both
    stacks are in the pure eager-flood regime; the checked-in Go
    artifact has no anti-entropy at all, see
    test_go_binary_has_no_anti_entropy.)"""
    want = analytic_flood_count(25, 13)
    msgs_go, reads_go = run_broadcast_flood(lambda i: [GO_BROADCAST],
                                            n=25, n_values=13)
    msgs_py, reads_py = run_broadcast_flood(
        lambda i: PY + ["gossip_glomers_tpu.nodes.broadcast"],
        n=25, n_values=13, extra_env={"GG_SYNC_INTERVAL": "600"})
    assert all(r == list(range(13)) for r in reads_go.values())
    assert reads_go == reads_py
    assert msgs_go["broadcast"] == msgs_py["broadcast"] == want
    assert msgs_go["broadcast_ok"] == msgs_py["broadcast_ok"] == want
    assert msgs_go == msgs_py


@needs_go
def test_fatal_input_parity_go_vs_ours():
    """Both implementations die (exit 1) on malformed JSON and on a
    message type with no handler — the reference lib returns the error
    from Run and every main() exits via log.Fatal."""
    import subprocess

    # same scrub ProcessNetwork applies: without it the image's
    # sitecustomize registers the TPU plugin in every child
    env = {k: v for k, v in os.environ.items()
           if k != "PALLAS_AXON_POOL_IPS"}
    init = json.dumps({"src": "c1", "dest": "n0",
                       "body": {"type": "init", "msg_id": 1,
                                "node_id": "n0", "node_ids": ["n0"]}})
    bogus = json.dumps({"src": "c1", "dest": "n0",
                        "body": {"type": "no_such_op", "msg_id": 2}})
    for argv in ([GO_BROADCAST],
                 PY + ["gossip_glomers_tpu.nodes.broadcast"]):
        for payload in ("this is not json\n", init + "\n" + bogus + "\n"):
            p = subprocess.Popen(argv, stdin=subprocess.PIPE,
                                 stdout=subprocess.DEVNULL,
                                 stderr=subprocess.DEVNULL,
                                 text=True, env=env)
            try:
                p.stdin.write(payload)
                p.stdin.flush()
                assert p.wait(timeout=15) == 1, (argv, payload)
            finally:
                p.kill()


@needs_go
def test_go_binary_has_no_anti_entropy():
    """Artifact/source discrepancy, pinned: the checked-in
    maelstrom-broadcast binary never runs the SyncBroadcast timer the
    checked-in source has (broadcast/main.go:42-51) — two diverged sets
    stay diverged past several 2-3 s timer periods with zero read
    traffic.  Like the kafka binary (see test_go_kafka_semantics), the
    artifact predates its source; the SOURCE is the authoritative
    reference for anti-entropy, certified against our two stacks in
    test_sync_waves_process_vs_virtual_vs_analytic."""
    import time

    net = ProcessNetwork()
    try:
        for i in range(2):
            net.spawn(f"n{i}", [GO_BROADCAST])
        net.init_cluster()
        net.set_topology({"n0": [], "n1": []})   # keep the value local
        net.rpc("n0", {"type": "broadcast", "message": 42})
        from gossip_glomers_tpu.parallel.topology import line
        net.set_topology(to_name_map(line(2)))   # reconnect
        time.sleep(6.5)                          # > 2 full timer periods
        assert net.server_msgs_by_type.get("read", 0) == 0
        assert not net.rpc("n1", {"type": "read"}).get("messages")
    finally:
        net.shutdown()


@needs_go
def test_go_binaries_flood_count_and_convergence():
    msgs, reads = run_broadcast_flood(lambda i: [GO_BROADCAST])
    assert all(r == list(range(8)) for r in reads.values())
    assert msgs["broadcast"] == analytic_flood_count()
    # every server-to-server broadcast is acked (broadcast.go:69,78)
    assert msgs["broadcast_ok"] == msgs["broadcast"]


def test_our_stdio_nodes_match_go_flood_counts():
    msgs, reads = run_broadcast_flood(
        lambda i: PY + ["gossip_glomers_tpu.nodes.broadcast"])
    assert all(r == list(range(8)) for r in reads.values())
    assert msgs["broadcast"] == analytic_flood_count()
    assert msgs["broadcast_ok"] == msgs["broadcast"]


def test_virtual_harness_matches_go_flood_counts():
    from gossip_glomers_tpu.harness.workloads import run_broadcast

    res = run_broadcast(n_nodes=5, topology="tree", n_values=8,
                        rate=100.0, quiescence=0.5, seed=0)
    assert res.ok
    # quiescence kept below the 2 s anti-entropy timer: eager flood only
    by_type = res.stats["by_type"]
    assert res.stats["server_msgs_at_quiescence"] == \
        2 * analytic_flood_count()  # broadcast + broadcast_ok
    assert by_type["broadcast"] - 8 == analytic_flood_count()  # -client ops


# -- anti-entropy regime: process vs virtual vs analytic ----------------
#
# The reference's sync (SyncBroadcast, broadcast.go:81-122 + the 2 s
# timer, main.go:42-51) decides msgs/op in steady state.  The checked-in
# Go binary predates that code (test_go_binary_has_no_anti_entropy), so
# the source-derived analytic count is the reference line, and both our
# stacks must hit it exactly on a pinned-timer, staggered-anchor
# schedule:
#
#   - 25-node 4-ary tree, sync_jitter=0 -> node i's waves fire at
#     init_i + k*SYNC_T.  n24 (a leaf) is initialized 0.35 s after the
#     rest, so its parent n5 always syncs first.  SYNC_T=4 (not the
#     reference's 2 s) buys wall-clock margin on loaded machines; the
#     expected counts are interval-independent (they cover exactly two
#     waves), and explicit precondition asserts below turn a too-slow
#     spawn/flood into a clear failure instead of a count mismatch.
#   - values 0..9 flood healthy; value 10 floods while n24 is
#     partitioned off (its copy drops in-network); heal before the
#     first wave.
#   - wave 1: every node reads every neighbor (read/read_ok = sum of
#     degrees = 48).  n5 sees n24 lacks 10 -> one targeted push
#     (broadcast + broadcast_ok, broadcast.go:104-108); n24 is a leaf
#     so its own learn re-floods nothing (:97-102 fans to zero other
#     neighbors).  wave 2: all sets equal -> reads only.
#
# Expected server-to-server counts over floods + exactly 2 waves:
#   broadcast     11*24 + 1  = 265   (flood sends count even when
#   broadcast_ok  10*24+23+1 = 264    dropped; delivered ones are acked)
#   read/read_ok  2 * 48     = 96 each

SYNC_WAVE_EXPECT = {"broadcast": 265, "broadcast_ok": 264,
                    "read": 96, "read_ok": 96}
SYNC_T = 4.0   # pinned sync interval for both scenario backends


def _wait_msgs(net, pred, deadline_s: float, what: str,
               poll: float = 0.05) -> None:
    """Event-driven wait: poll the server-message ledger until ``pred``
    (on server_msgs_by_type) holds, failing loudly at the deadline —
    the loaded-machine-proof replacement for fixed sleeps."""
    import time

    t_end = time.monotonic() + deadline_s
    while time.monotonic() < t_end:
        if pred(dict(net.server_msgs_by_type)):
            return
        time.sleep(poll)
    raise AssertionError(
        f"timed out after {deadline_s:.1f}s waiting for {what}; "
        f"ledger: {dict(net.server_msgs_by_type)}")


def _sync_wave_scenario_process():
    import time
    from concurrent.futures import ThreadPoolExecutor

    env = {"GG_SYNC_INTERVAL": str(int(SYNC_T)), "GG_SYNC_JITTER": "0"}
    blocked = {"on": False}
    net = ProcessNetwork(
        drop_fn=lambda src, dest, now: (blocked["on"]
                                        and "n24" in (src, dest)))
    try:
        ids = [f"n{i}" for i in range(25)]
        with ThreadPoolExecutor(max_workers=16) as pool:
            list(pool.map(
                lambda i: net.spawn(
                    f"n{i}", PY + ["gossip_glomers_tpu.nodes.broadcast"],
                    extra_env=env), range(25)))
        # anchors: n0..n23 now, n24 later -> n5's waves precede n24's
        t_first = time.monotonic()   # lower bound on every init_i
        for i in range(24):
            rep = net.rpc(f"n{i}", {"type": "init", "node_id": f"n{i}",
                                    "node_ids": ids})
            assert rep["type"] == "init_ok"
        time.sleep(0.35)
        rep = net.rpc("n24", {"type": "init", "node_id": "n24",
                              "node_ids": ids})
        assert rep["type"] == "init_ok"
        t24 = time.monotonic()
        # clearance before earliest wave 3 (>= t_first+3T) is
        # T - 0.7 - (t24 - t_first); this bound guarantees > 1 s
        assert t24 - t_first < SYNC_T - 1.7, (
            "scenario precondition: node inits took "
            f"{t24 - t_first:.2f}s; the wave-window cut at t24+2T+0.7 "
            "would overlap wave 3 — machine too loaded for this test")
        net.set_topology(to_name_map(tree(25)))
        for v in range(10):
            rep = net.rpc(f"n{v % 25}", {"type": "broadcast",
                                         "message": v})
            assert rep["type"] == "broadcast_ok"
        net.quiesce(idle=0.15, timeout=3.0)
        blocked["on"] = True
        rep = net.rpc("n0", {"type": "broadcast", "message": 10})
        assert rep["type"] == "broadcast_ok"
        net.quiesce(idle=0.15, timeout=3.0)   # flood done, n24's copy lost
        blocked["on"] = False                 # heal before the first wave
        assert time.monotonic() < t_first + SYNC_T - 0.3, (
            "scenario precondition: flood + partition window did not "
            "finish before the first sync wave — machine too loaded")
        assert not net.rpc("n24", {"type": "read"}).get("messages",
                                                        []).count(10)
        # event-driven wave-2 wait: both waves' read fan-outs total 96
        # (2 x sum of degrees); poll the ledger for the last of them
        # (n24's wave 2 at ~t24+2T) instead of sleeping a fixed window,
        # then drain the trailing read_oks/acks via idle detection.
        # Deadline = just before anyone's wave 3 (earliest ~t_first+3T;
        # the init precondition above guarantees >1s of clearance).
        deadline = (t_first + 3 * SYNC_T - 0.6) - time.monotonic()
        _wait_msgs(net,
                   lambda m: m.get("read", 0)
                   >= SYNC_WAVE_EXPECT["read"],
                   deadline, "both sync waves' reads")
        net.quiesce(idle=0.25, timeout=2.0)
        snap = dict(net.server_msgs_by_type)
        r24 = sorted(net.rpc("n24", {"type": "read"})["messages"])
        return snap, r24
    finally:
        net.shutdown()


def _sync_wave_scenario_virtual():
    from gossip_glomers_tpu.harness.network import VirtualNetwork
    from gossip_glomers_tpu.models import BroadcastProgram
    from gossip_glomers_tpu.utils.config import (BroadcastConfig,
                                                 NetConfig)

    net = VirtualNetwork(NetConfig(latency=0.0, seed=0))
    for i in range(25):
        net.spawn(f"n{i}",
                  BroadcastProgram(BroadcastConfig(sync_interval=SYNC_T,
                                                   sync_jitter=0.0)))
    blocked = {"on": False}
    net.drop_fn = (lambda src, dest, now: blocked["on"]
                   and "n24" in (src, dest))
    ids = sorted(net.nodes)
    ctl = net.client("c0")
    for i in range(24):
        ctl.rpc(f"n{i}", {"type": "init", "node_id": f"n{i}",
                          "node_ids": ids})
    net.run_for(0.35)
    ctl.rpc("n24", {"type": "init", "node_id": "n24", "node_ids": ids})
    net.run_for(0.0)
    net.set_topology(to_name_map(tree(25)))
    client = net.client("c1")
    for v in range(10):
        client.rpc(f"n{v % 25}", {"type": "broadcast", "message": v})
        net.run_for(0.01)
    blocked["on"] = True
    client.rpc("n0", {"type": "broadcast", "message": 10})
    net.run_for(0.05)
    blocked["on"] = False
    # waves: n0..n23 at t=T, 2T; n24 at T+.35, 2T+.35; cut before 3T
    net.run_for(2 * SYNC_T + 0.8 - net.now)
    snap = dict(net.ledger.server_msgs_by_type)
    got: dict[str, list] = {}
    client.rpc("n24", {"type": "read"},
               lambda rep: got.__setitem__("m", rep.body["messages"]))
    net.run_for(0.0)
    return snap, sorted(got["m"])


def test_sync_waves_process_vs_virtual_vs_analytic():
    snap_v, r24_v = _sync_wave_scenario_virtual()
    assert r24_v == list(range(11))          # the hole was repaired
    assert snap_v == SYNC_WAVE_EXPECT
    # the process scenario's wall-clock preconditions ("scenario
    # precondition: ... machine too loaded") are environmental, not
    # correctness claims — 25 interpreter spawns can exceed the wave
    # budget on a saturated single-core CI box.  Retry those; any
    # other failure is real and stays fatal.
    last = None
    for _ in range(3):
        try:
            snap_p, r24_p = _sync_wave_scenario_process()
            break
        except AssertionError as e:
            if "scenario precondition" not in str(e):
                raise
            last = e
    else:
        pytest.skip(f"machine too loaded for the 25-process "
                    f"wall-clock scenario: {last}")
    assert r24_p == list(range(11))
    assert snap_p == snap_v == SYNC_WAVE_EXPECT


def _counter_session(argv):
    """2 nodes + seq-kv: three adds, then poll reads until the 200 ms
    flush cadence and 700 ms read-poll (add.go:62, counter/main.go:53)
    have propagated the sum to both nodes' caches — event-driven with a
    deadline, not a fixed sleep (reads are local-cache-only,
    add.go:29-31, so polling does not perturb the flush path)."""
    import time

    net = ProcessNetwork()
    try:
        net.add_kv("seq-kv")
        for i in range(2):
            net.spawn(f"n{i}", list(argv))
        net.init_cluster()
        for d in (3, 4, 5):
            rep = net.rpc(f"n{d % 2}", {"type": "add", "delta": d})
            assert rep["type"] == "add_ok"

        def read_both():
            return [net.rpc(f"n{i}", {"type": "read"})["value"]
                    for i in range(2)]

        vals = read_both()
        t_end = time.monotonic() + 8.0
        while vals != [12, 12] and time.monotonic() < t_end:
            time.sleep(0.2)
            vals = read_both()
        return vals
    finally:
        net.shutdown()


@needs_go
def test_go_counter_semantics():
    assert _counter_session([GO_COUNTER]) == [12, 12]


def test_our_counter_matches_go_semantics():
    assert _counter_session(
        PY + ["gossip_glomers_tpu.nodes.counter"]) == [12, 12]


def _kafka_session(argv, poll_field, poll_from):
    """The checked-in Go kafka binary predates the checked-in source: it
    replies to poll with field ``offsets`` (and returns nothing for
    from-offset 0), where kafka/log.go:85-88 says ``msgs`` (and returns
    everything >= the requested offset).  The source is the authoritative
    reference; our node follows it.  The session parameterizes over the
    dialect so both stacks are checked for the same content."""
    net = ProcessNetwork()
    try:
        net.add_kv("lin-kv")
        for i in range(2):
            net.spawn(f"n{i}", list(argv))
        net.init_cluster()
        offs = [net.rpc("n0", {"type": "send", "key": "k1",
                               "msg": 100 + j})["offset"]
                for j in range(3)]
        net.quiesce(idle=0.3, timeout=5.0)
        poll0 = net.rpc("n0", {"type": "poll",
                               "offsets": {"k1": poll_from}})[poll_field]
        poll1 = net.rpc("n1", {"type": "poll",
                               "offsets": {"k1": poll_from}})[poll_field]
        net.rpc("n0", {"type": "commit_offsets",
                       "offsets": {"k1": offs[-1]}})
        listed = net.rpc("n0", {"type": "list_committed_offsets",
                                "keys": ["k1"]})["offsets"]
        return offs, poll0, poll1, listed
    finally:
        net.shutdown()


@needs_go
def test_go_kafka_semantics():
    # The artifact is older still than its poll dialect suggests: it
    # allocates offsets locally (no lin-kv traffic) and does not
    # replicate to peers at all — a single-node-stage build (the 5a
    # solution in the challenge progression).  Assert the behavior the
    # artifact actually has; full replicated semantics are asserted
    # against kafka/log.go via our node below.
    offs, poll0, poll1, listed = _kafka_session(
        [GO_KAFKA], poll_field="offsets", poll_from=1)
    assert offs == [1, 2, 3]
    assert poll0["k1"] == [[1, 100], [2, 101], [3, 102]]
    assert poll1["k1"] == []  # no replication in this build
    assert listed == {"k1": 3}


def test_our_kafka_matches_go_semantics():
    # our node speaks the source dialect: "msgs", from-offset 0 = all
    offs, poll0, poll1, listed = _kafka_session(
        PY + ["gossip_glomers_tpu.nodes.kafka"],
        poll_field="msgs", poll_from=0)
    assert offs == [1, 2, 3]
    assert poll0["k1"] == [[1, 100], [2, 101], [3, 102]]
    assert poll1["k1"] == poll0["k1"]
    assert listed == {"k1": 3}


@needs_go
def test_mixed_workload_msgs_per_op_ours_beats_or_matches_go():
    """The head-to-head behind BENCH_ALL's process-head-to-head rows
    (benchmarks/process_mix.py): the identical mixed broadcast+read
    stream through the shared router against both stacks — under
    Maelstrom accounting (server msgs / ALL client ops) our flood-
    regime number must equal the Go artifact's exactly (both are the
    deterministic eager flood), i.e. ours <= Go's."""
    import pathlib
    import sys as _sys

    _sys.path.insert(0, str(pathlib.Path(__file__).resolve().parent.parent))
    from benchmarks.process_mix import GO_BROADCAST, PY_NODE, run_mix

    kw = dict(n_nodes=5, topology="tree", rate=40.0, duration=3.0,
              read_share=0.5, seed=0, quiesce_s=0.5)
    go = run_mix([GO_BROADCAST], **kw)
    ours = run_mix(PY_NODE, extra_env={"GG_SYNC_INTERVAL": "600"}, **kw)
    assert go["ok"] and ours["ok"]
    assert ours["n_ops"] == go["n_ops"]
    # load-robust invariants (the two stacks run sequentially, so a
    # direct ours <= go assert would couple two independent wall-clock
    # sessions' load): ANY correct flood pays at least the analytic
    # floor (8 server msgs per value on a 5-node tree: 4 broadcasts +
    # 4 acks), so pinning ours within 10% of the floor pins
    # ours <= 1.1 * go for any Go run.  The direct measured ours-vs-Go
    # rows live in BENCH_ALL configs 1p/2p (benchmarks/process_mix.py).
    floor = 8 * ours["n_broadcast"]
    assert go["server_msgs"] >= floor
    assert floor <= ours["server_msgs"] <= 1.1 * floor
