"""Device-resident KV store (tpu_sim/kvstore.py, PR 14): stateless-hash
routing parity between host and device, masked CAS/write semantics over
the sharded key rows, the counter/kafka ``kv_backend='device'``
bit-exact pins against the host path (single-device AND the 8-way
virtual mesh), crash-amnesia row wipes, loud dup-stream rejection
(ROADMAP item 6), the zero-all-gather audit contract, and the declared
traced/host split's totality under the determinism lint.
"""

import ast as ast_mod
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import gossip_glomers_tpu
from gossip_glomers_tpu.tpu_sim import CounterSim, KafkaSim
from gossip_glomers_tpu.tpu_sim import audit, faults
from gossip_glomers_tpu.tpu_sim import kvstore as KV
from gossip_glomers_tpu.tpu_sim import txn as TX
from gossip_glomers_tpu.tpu_sim.engine import collectives

PKG_DIR = os.path.dirname(gossip_glomers_tpu.__file__)


def mesh_8() -> Mesh:
    return Mesh(np.array(jax.devices()).reshape(8), ("nodes",))


# -- routing + layout ----------------------------------------------------


def test_owner_routing_host_device_bit_exact():
    keys = np.arange(257, dtype=np.int32)
    for n, seed in ((5, 0), (8, 3), (32, 11)):
        host = KV.host_owner_of(keys, n, seed)
        dev = np.asarray(KV.owner_of(jnp.asarray(keys), n, seed))
        assert (host == dev).all(), (n, seed)
        assert host.min() >= 0 and host.max() < n
    # distinct seeds re-deal the keys (the hash really consumes seed)
    a = KV.host_owner_of(keys, 8, 0)
    b = KV.host_owner_of(keys, 8, 1)
    assert (a != b).any()


def test_make_layout_places_every_key_exactly_once():
    n_keys, n = 40, 7
    lay = KV.make_layout(n_keys, n, seed=2)
    assert lay.key_at.shape == (n, lay.cap)
    seen = set()
    for k in range(n_keys):
        i, c = int(lay.owner[k]), int(lay.slot[k])
        assert lay.key_at[i, c] == k
        seen.add((i, c))
    assert len(seen) == n_keys
    assert int((lay.key_at >= 0).sum()) == n_keys   # empties are -1
    # owners come from the routing hash itself
    assert (lay.owner
            == KV.host_owner_of(np.arange(n_keys), n, 2)).all()


def test_stale_coin_host_device_bit_exact():
    ids = np.arange(64, dtype=np.int32)
    for seed, t in ((0, 0), (3, 5), (123, 31)):
        dev = np.asarray(KV.stale_coin(seed, jnp.int32(t),
                                       jnp.asarray(ids)))
        host = KV.host_stale_coin(seed, t, ids)
        assert (dev == host).all(), (seed, t)
    # threshold convention: prob 0 never fires, prob 1 always fires
    assert int(KV.stale_num_of(0.0)) == 0
    h = KV.host_stale_coin(0, 0, ids)
    assert (h < KV.stale_num_of(1.0)).all()


# -- CAS / write semantics -----------------------------------------------


def test_cas_write_and_version_semantics():
    n, k = 3, 6
    lay = KV.make_layout(k, n, seed=0)
    ka = jnp.asarray(lay.key_at)
    coll = collectives(n)

    def view(rows):
        return np.asarray(KV.rows_view(rows, ka, k, coll.reduce_sum))

    rows = KV.init_rows(lay)
    v = view(rows)
    assert v.shape == (2, k) and (v == 0).all()

    on = jnp.asarray(np.ones(k, bool))
    rows = KV.write_apply(rows, ka, on, jnp.full((k,), 7, jnp.int32))
    v = view(rows)
    assert (v[0] == 7).all() and (v[1] == 1).all()

    # value-compare CAS: hit on key 2 only (frm matches), miss elsewhere
    frm = np.zeros(k, np.int32)
    frm[2] = 7
    rows = KV.cas_apply(rows, ka, on, jnp.asarray(frm),
                        jnp.full((k,), 9, jnp.int32))
    v = view(rows)
    others = [i for i in range(k) if i != 2]
    assert v[0, 2] == 9 and v[1, 2] == 2
    assert (v[0, others] == 7).all() and (v[1, others] == 1).all()

    # version-compare CAS (the txn commit primitive): hit where ver==1
    rows = KV.cas_ver_apply(rows, ka, on, jnp.ones((k,), jnp.int32),
                            jnp.full((k,), 11, jnp.int32))
    v = view(rows)
    assert v[0, 2] == 9 and v[1, 2] == 2                    # ver 2: miss
    assert (v[0, others] == 11).all() and (v[1, others] == 2).all()

    # masked off: nothing moves
    rows2 = KV.cas_apply(rows, ka, jnp.zeros((k,), bool),
                         jnp.asarray(v[0]), jnp.asarray(v[0] + 1))
    assert (np.asarray(rows2.vals) == np.asarray(rows.vals)).all()
    assert (np.asarray(rows2.vers) == np.asarray(rows.vers)).all()


def test_rows_wipe_fires_on_the_amnesia_coin_only():
    n, k = 4, 8
    spec = faults.NemesisSpec(n_nodes=n, seed=0, crash=((1, 3, (2,)),))
    plan = spec.compile()
    lay = KV.make_layout(k, n, seed=1)
    vals = jnp.arange(n * lay.cap, dtype=jnp.int32).reshape(n, lay.cap)
    rows = KV.KVRows(vals=vals + 1, vers=jnp.ones_like(vals))
    ids = jnp.arange(n, dtype=jnp.int32)
    wiped_rounds = []
    for t in range(6):
        out = KV.rows_wipe(rows, plan, jnp.int32(t), ids)
        zeroed = np.asarray(out.vals == 0).all(axis=1)
        assert not zeroed[[0, 1, 3]].any(), t   # only the crashed node
        if zeroed[2]:
            wiped_rounds.append(t)
            assert np.asarray(out.vers)[2].sum() == 0
    # exactly one restart edge inside the horizon
    assert len(wiped_rounds) == 1


# -- counter: device backend bit-exact vs host ---------------------------


def _counter_pair(n, spec, **kw):
    return [CounterSim(n, mode="cas", seed=7,
                       fault_plan=spec.compile(), kv_backend=b, **kw)
            for b in ("host", "device")]


def test_counter_device_backend_bit_exact_vs_host():
    n, rounds = 8, 12
    spec = faults.NemesisSpec(n_nodes=n, seed=4, crash=((1, 3, (2,)),),
                              loss_rate=0.2, loss_until=5)
    sims = _counter_pair(n, spec, poll_every=2)
    deltas = np.arange(1, n + 1, dtype=np.int32)
    states = [s.add(s.init_state(), deltas) for s in sims]
    for t in range(rounds):
        states = [s.step(st) for s, st in zip(sims, states)]
        h, d = states
        assert (np.asarray(h.pending) == np.asarray(d.pending)).all(), t
        assert (np.asarray(h.cached) == np.asarray(d.cached)).all(), t
        assert int(h.kv) == int(d.kv), t
        assert int(h.msgs) == int(d.msgs), t
    # node 2's acked-but-unflushed delta died with its crash (the
    # ack-before-durability risk — node-state amnesia, SAME on both
    # backends); everything else landed
    assert int(states[1].kv) == int(deltas.sum()) - int(deltas[2])
    # the sharded rows agree with the carried scalar (store == truth)
    lay = sims[1]._kv_layout
    i, c = int(lay.owner[0]), int(lay.slot[0])
    assert (int(np.asarray(states[1].rows.vals)[i, c])
            == int(states[1].kv))
    # the fused driver lands the identical ledger and value
    st_f = sims[1].run_fused(
        sims[1].add(sims[1].init_state(), deltas), rounds)
    assert int(st_f.msgs) == int(states[1].msgs)
    assert int(st_f.kv) == int(states[1].kv)


def test_counter_device_backend_bit_exact_on_8way_mesh():
    n, rounds = 16, 10
    spec = faults.NemesisSpec(n_nodes=n, seed=9, crash=((2, 4, (5,)),),
                              loss_rate=0.15, loss_until=6)
    single = CounterSim(n, mode="cas", poll_every=2, seed=3,
                        fault_plan=spec.compile(), kv_backend="device")
    sharded = CounterSim(n, mode="cas", poll_every=2, seed=3,
                         fault_plan=spec.compile(),
                         kv_backend="device", mesh=mesh_8())
    deltas = np.arange(1, n + 1, dtype=np.int32)
    a = single.add(single.init_state(), deltas)
    b = sharded.add(sharded.init_state(), deltas)
    for t in range(rounds):
        a, b = single.step(a), sharded.step(b)
        assert (np.asarray(a.pending) == np.asarray(b.pending)).all(), t
        assert (np.asarray(a.cached) == np.asarray(b.cached)).all(), t
        assert int(a.kv) == int(b.kv), t
        assert int(a.msgs) == int(b.msgs), t
        assert (np.asarray(a.rows.vals) == np.asarray(b.rows.vals)).all()


def test_counter_kv_amnesia_loses_acked_flushes():
    """kv_amnesia composes the FaultPlan's restart coin into the KV
    rows: the crashed OWNER's registers die with it, so sums flushed
    before the wipe are genuinely lost — the durable-service twin
    (default) keeps them.  This is the falsifiable direction of the
    KVService pin: amnesia MUST diverge."""
    n = 6
    owner = int(KV.host_owner_of(np.array([0]), n, 7)[0])
    spec = faults.NemesisSpec(n_nodes=n, seed=2,
                              crash=((1, 3, (owner,)),))
    durable, amnesic = (
        CounterSim(n, mode="cas", poll_every=0, seed=7,
                   fault_plan=spec.compile(), kv_backend="device",
                   kv_amnesia=flag)
        for flag in (False, True))
    deltas = np.arange(1, n + 1, dtype=np.int32)
    # the crashing owner contributes nothing itself, so its node-state
    # amnesia (pending wipe, both flags) cannot mask the ROW wipe —
    # any shortfall below is lost COMMITTED sums, not lost acks
    deltas[owner] = 0
    std = durable.run(durable.add(durable.init_state(), deltas), n + 4)
    sta = amnesic.run(amnesic.add(amnesic.init_state(), deltas), n + 4)
    assert int(std.kv) == int(deltas.sum())        # durable: all there
    assert 0 < int(sta.kv) < int(deltas.sum())     # amnesia: real loss


# -- kafka: device backend bit-exact vs host -----------------------------


def _drive_kafka(sim, mesh=None):
    """A scripted allocator/commit dance; returns the observable trail
    (lin-kv cells, per-node committed HWMs, ledger) after each phase."""
    n = 8
    st = sim.init_state()
    trail = []

    def snap(st):
        trail.append((sim.lin_kv(st),
                      {i: sim.list_committed(st, i) for i in range(n)},
                      int(st.msgs)))

    # phase A: burst sends on key 0 (nodes 0-3) + key 1 (nodes 4-5)
    sk = np.full((n, 1), -1, np.int32)
    sv = np.zeros((n, 1), np.int32)
    sk[0:4, 0] = 0
    sk[4:6, 0] = 1
    sv[0:6, 0] = np.arange(10, 16, dtype=np.int32)
    st = sim.step(st, sk, sv)
    snap(st)
    # phase B: commit dances — active, overshoot-learn, local-skip
    cr = np.full((n, 2), -1, np.int32)
    cr[0, 0] = 2
    cr[6, 0] = 1
    cr[4, 1] = 1
    st = sim.step(st, commit_req=cr)
    snap(st)
    # phase C: a second send wave + a contended commit CAS
    sk2 = np.full((n, 1), -1, np.int32)
    sv2 = np.zeros((n, 1), np.int32)
    sk2[7, 0] = 0
    sv2[7, 0] = 99
    st = sim.step(st, sk2, sv2)
    cr2 = np.full((n, 2), -1, np.int32)
    cr2[2, 0] = 4
    cr2[3, 0] = 4
    st = sim.step(st, commit_req=cr2)
    snap(st)
    trail.append([sim.poll(st, i, 0, 0) for i in range(n)])
    return trail


def test_kafka_device_backend_bit_exact_vs_host():
    host = KafkaSim(8, 2, capacity=32, max_sends=1)
    dev = KafkaSim(8, 2, capacity=32, max_sends=1, kv_backend="device")
    assert _drive_kafka(host) == _drive_kafka(dev)


def test_kafka_device_backend_bit_exact_on_8way_mesh():
    single = KafkaSim(8, 2, capacity=32, max_sends=1,
                      kv_backend="device")
    sharded = KafkaSim(8, 2, capacity=32, max_sends=1,
                       kv_backend="device", mesh=mesh_8())
    assert _drive_kafka(single) == _drive_kafka(sharded)


# -- dup-stream rejection (ROADMAP item 6, the still-open half) ---------


def test_device_backend_rejects_dup_streams_loudly():
    dup = faults.NemesisSpec(n_nodes=4, seed=0, dup_rate=0.2,
                             dup_until=4)
    with pytest.raises(ValueError, match="dup"):
        CounterSim(4, mode="cas", kv_backend="device",
                   fault_plan=dup.compile())
    with pytest.raises(ValueError, match="dup"):
        KafkaSim(4, 2, capacity=16, kv_backend="device",
                 fault_plan=dup.compile())
    with pytest.raises(ValueError, match="dup"):
        TX.TxnSim(4, 8, fault_plan=dup.compile())
    # the host backend keeps its id-correlated dedup semantics
    CounterSim(4, mode="cas", fault_plan=dup.compile())
    # and loss+crash plans stay accepted on the device backend
    ok = faults.NemesisSpec(n_nodes=4, seed=0, loss_rate=0.2,
                            loss_until=4, crash=((1, 2, (0,)),))
    CounterSim(4, mode="cas", kv_backend="device",
               fault_plan=ok.compile())


# -- audit contract: the zero-all-gather HLO gate -----------------------


def test_kvstore_sharded_cas_contract_is_all_reduce_only():
    cs = {c.name: c for c in KV.audit_contracts()}
    res = audit.audit_contract(cs["kvstore/sharded-cas-step"], mesh_8())
    assert res["ok"], res
    counts = res["checks"]["collectives"]["counts"]
    assert counts.get("all-gather", 0) == 0
    assert counts.get("all-reduce", 0) >= 1


# -- declared traced/host split (determinism lint) ----------------------


def test_kvstore_traced_host_split_is_total():
    src = open(os.path.join(PKG_DIR, "tpu_sim", "kvstore.py")).read()
    tree = ast_mod.parse(src)
    top_fns = {node.name for node in tree.body
               if isinstance(node, ast_mod.FunctionDef)}
    declared = set(KV.TRACED_EVALUATORS) | set(KV.HOST_SIDE)
    assert top_fns == declared, (
        f"undeclared: {sorted(top_fns - declared)}, "
        f"stale: {sorted(declared - top_fns)}")
    pat = audit._root_pattern_for("tpu_sim/kvstore.py")
    for name in KV.TRACED_EVALUATORS:
        assert pat.match(name), name
    for name in KV.HOST_SIDE:
        assert not pat.match(name), name
