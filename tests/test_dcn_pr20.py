"""DCN latency hiding (PR 20): pipelined + stale-by-k mode pins.

Three layers, all single-process (the real 2-process cluster legs
live in ``tests/test_dcn.py`` and ``scripts/dcn_smoke.py``):

- **Bit-exact pipelining**: every sim's round under
  ``dcn_mode="pipelined"`` on the hierarchical mesh produces the
  IDENTICAL state checksums as (a) its own synchronous twin and (b)
  the flat-mesh run, where pipelining is a structural no-op — so the
  equality is a bit-exactness claim about the double-buffered
  half-block DCN circuits, not a tolerance.  Includes the H=3
  NON-power-of-two host count (ring fallback on the hosts axis) the
  2-host CI cluster cannot cover.
- **Certified staleness**: a ``stale:4`` counter allreduce crash+loss
  campaign converges within k rounds of its sync twin with zero lost
  acked writes (``check_staleness_bound``), the planted k-violation
  FAILS naming the violating round, and a failing stale run's flight
  bundle records the mode and replays it (``replay_bundle(...,
  mesh=)``).
- **The refusal matrix**: every surface whose staleness semantics are
  undecided refuses loudly at construction — kafka offset allocation,
  txn wound-or-die, broadcast delivery, counter cas / device-KV /
  observed+traffic calibration, scenario/serving batches, flat
  meshes, carry-less bare modes — plus the env-knob and mode-grammar
  error contracts and the ``check_staleness_bound`` falsifiability
  units.
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from gossip_glomers_tpu.harness.checkers import check_staleness_bound
from gossip_glomers_tpu.harness.nemesis import run_counter_nemesis
from gossip_glomers_tpu.parallel.dcn_worker import state_digest
from gossip_glomers_tpu.parallel.mesh import pick_mesh, pick_mesh_2d
from gossip_glomers_tpu.parallel.topology import grid, to_padded_neighbors
from gossip_glomers_tpu.tpu_sim import engine
from gossip_glomers_tpu.tpu_sim.engine import (DCN_SYNC, DcnMode, DcnRound,
                                               dcn_mode_from_env,
                                               resolve_dcn_mode)
from gossip_glomers_tpu.tpu_sim.faults import NemesisSpec

# the certified staleness spec (scripts/dcn_smoke.py uses the same
# one): crash+loss through round 5; under stale:4 the last drained
# deltas wait for a refresh round, so the run converges EXACTLY 2
# rounds after the sync twin — inside the bound, but measurably late
STALE_SPEC = NemesisSpec(n_nodes=16, seed=3, crash=((1, 4, (2, 11)),),
                         loss_rate=0.2, loss_until=5)


# -- mode grammar / env knobs -------------------------------------------


def test_resolve_dcn_mode_grammar():
    assert resolve_dcn_mode("sync") == DCN_SYNC
    assert resolve_dcn_mode("pipelined") == DcnMode(pipeline=True)
    assert resolve_dcn_mode("stale:3") == DcnMode(stale_k=3)
    both = resolve_dcn_mode("pipelined+stale:2")
    assert both == DcnMode(pipeline=True, stale_k=2)
    # label round-trips through the same grammar (what runner_kw and
    # flight bundles record)
    for s in ("sync", "pipelined", "stale:3", "pipelined+stale:2"):
        assert resolve_dcn_mode(resolve_dcn_mode(s).label()).label() \
            == s
    assert DCN_SYNC.label() == "sync"
    with pytest.raises(ValueError, match="unknown part"):
        resolve_dcn_mode("fast")
    with pytest.raises(ValueError, match="stale"):
        resolve_dcn_mode("stale:x")
    with pytest.raises(ValueError, match=">= 0"):
        resolve_dcn_mode(DcnMode(stale_k=-1))
    with pytest.raises(ValueError, match="DcnMode"):
        resolve_dcn_mode(3)


def test_env_knobs_loud(monkeypatch):
    monkeypatch.delenv("GG_DCN_PIPELINE", raising=False)
    monkeypatch.delenv("GG_DCN_STALE_K", raising=False)
    assert dcn_mode_from_env() == DCN_SYNC
    monkeypatch.setenv("GG_DCN_PIPELINE", "1")
    monkeypatch.setenv("GG_DCN_STALE_K", "3")
    assert dcn_mode_from_env() == DcnMode(pipeline=True, stale_k=3)
    # non-integers and out-of-range values refuse NAMING the variable
    monkeypatch.setenv("GG_DCN_PIPELINE", "yes")
    with pytest.raises(ValueError, match="GG_DCN_PIPELINE"):
        dcn_mode_from_env()
    monkeypatch.setenv("GG_DCN_PIPELINE", "2")
    with pytest.raises(ValueError, match="GG_DCN_PIPELINE"):
        dcn_mode_from_env()
    monkeypatch.setenv("GG_DCN_PIPELINE", "0")
    monkeypatch.setenv("GG_DCN_STALE_K", "-1")
    with pytest.raises(ValueError, match="GG_DCN_STALE_K"):
        dcn_mode_from_env()


def test_dcn_chunks_round_trip():
    # the double buffer: two half blocks that join back losslessly,
    # odd sizes included; scalars and singletons decline the split
    for shape in ((8,), (7,), (3, 5), (2, 2, 2)):
        x = jnp.arange(int(np.prod(shape)), dtype=jnp.int32
                       ).reshape(shape)
        split = engine._dcn_chunks(x)
        assert split is not None
        (a, b), join = split
        assert a.shape[0] + b.shape[0] == x.size
        assert jnp.array_equal(join([a, b]), x)
    assert engine._dcn_chunks(jnp.int32(3)) is None
    assert engine._dcn_chunks(jnp.zeros((1,), jnp.int32)) is None


def test_dcn_round_carry_contracts():
    # probe mode records slot shapes without consuming a carry
    probe = DcnRound.probing("stale:2")
    assert probe._take(jnp.zeros((4,), jnp.int32)) is None
    assert [tuple(s.shape) for s in probe.shapes] == [(4,)]
    # a live stale context without the carried age refuses
    with pytest.raises(ValueError, match="age"):
        DcnRound("stale:2")
    # carry exhaustion and take/put mismatch both refuse loudly (the
    # round's collective structure changed without re-probing)
    ctx = DcnRound("stale:2", age=jnp.int32(0), carry=())
    with pytest.raises(ValueError, match="carry exhausted"):
        ctx._take(jnp.zeros((4,), jnp.int32))
    ctx2 = DcnRound("stale:2", age=jnp.int32(0),
                    carry=(jnp.zeros((1, 4), jnp.int32),))
    with pytest.raises(ValueError, match="carry mismatch"):
        ctx2.carry_out()


# -- check_staleness_bound falsifiability --------------------------------


def test_staleness_bound_certifies_within_k():
    ok, d = check_staleness_bound(
        stale_k=4, sync_converged_round=5, stale_converged_round=7,
        lost_writes=[])
    assert ok
    assert d["bound_round"] == 9 and d["delay_rounds"] == 2
    assert "violating_round" not in d


def test_staleness_bound_fails_past_k_naming_round():
    ok, d = check_staleness_bound(
        stale_k=1, sync_converged_round=5, stale_converged_round=7,
        lost_writes=[])
    assert not ok
    assert d["bound_round"] == 6 and d["violating_round"] == 7
    # never-converged is an unbounded violation, not a tie
    ok, d = check_staleness_bound(
        stale_k=4, sync_converged_round=5, stale_converged_round=None,
        lost_writes=[])
    assert not ok and d["violating_round"] == -1


def test_staleness_bound_lost_writes_and_recovery():
    # a lost acked write falsifies even inside the round bound
    ok, d = check_staleness_bound(
        stale_k=4, sync_converged_round=5, stale_converged_round=6,
        lost_writes=[{"lost_sum": 3}])
    assert not ok and d["n_lost_writes"] == 1
    # without a sync baseline only the lost-writes half is decidable
    ok, d = check_staleness_bound(
        stale_k=4, sync_converged_round=None,
        stale_converged_round=9, lost_writes=[])
    assert ok and d["bound_round"] is None
    # a failing composed recovery verdict fails the certification
    ok, d = check_staleness_bound(
        stale_k=4, sync_converged_round=5, stale_converged_round=6,
        lost_writes=[], recovery=(False, {"why": "x"}))
    assert not ok and d["recovery_ok"] is False
    with pytest.raises(ValueError, match=">= 0"):
        check_staleness_bound(stale_k=-1, sync_converged_round=1,
                              stale_converged_round=1, lost_writes=[])


# -- the refusal matrix --------------------------------------------------


def test_stale_refusals_undecided_surfaces():
    from gossip_glomers_tpu.tpu_sim import scenario
    from gossip_glomers_tpu.tpu_sim.broadcast import BroadcastSim
    from gossip_glomers_tpu.tpu_sim.counter import CounterSim
    from gossip_glomers_tpu.tpu_sim.kafka import KafkaSim
    from gossip_glomers_tpu.tpu_sim.txn import TxnSim

    hier = pick_mesh_2d(hosts=2)
    flat = pick_mesh()
    assert hier is not None and flat is not None
    nbrs = to_padded_neighbors(grid(16))
    with pytest.raises(ValueError, match="kafka has no"):
        KafkaSim(8, 4, capacity=32, mesh=hier, dcn_mode="stale:2")
    with pytest.raises(ValueError, match="txn has no"):
        TxnSim(8, 4, mesh=hier, dcn_mode="stale:2")
    with pytest.raises(ValueError, match="broadcast has no"):
        BroadcastSim(nbrs, n_values=16, mesh=hier, dcn_mode="stale:2")
    # counter: only the allreduce host-KV data plane is certified
    with pytest.raises(ValueError, match="allreduce"):
        CounterSim(16, mode="cas", mesh=hier, dcn_mode="stale:2")
    with pytest.raises(ValueError, match="host"):
        CounterSim(16, mode="allreduce", kv_backend="device",
                   mesh=hier, dcn_mode="stale:2")
    # a flat mesh has no DCN level to lag
    with pytest.raises(ValueError, match="hierarchical"):
        CounterSim(16, mode="allreduce", mesh=flat,
                   dcn_mode="stale:2")
    # scenario/serving batch dispatchers: no carry inside a cell
    with pytest.raises(ValueError, match="scenario batch"):
        scenario._refuse_stale_dcn("a scenario batch",
                                   {"dcn_mode": "stale:2"})
    # ... and the env contract is checked too
    os.environ["GG_DCN_STALE_K"] = "2"
    try:
        with pytest.raises(ValueError, match="GG_DCN_STALE_K"):
            scenario._refuse_stale_dcn("a serving batch")
    finally:
        del os.environ["GG_DCN_STALE_K"]


def test_engine_collectives_stale_refusals():
    hier = pick_mesh_2d(hosts=2)
    flat = pick_mesh()
    # a bare stale DcnMode without the driver-threaded carry refuses
    # (silently compiling the sync circuit would misreport the mode)
    with pytest.raises(ValueError, match="DcnRound"):
        engine.collectives(2, hier, dcn=DcnMode(stale_k=2))
    with pytest.raises(ValueError, match="hierarchical"):
        engine.collectives(2, flat, dcn=DcnMode(stale_k=2))
    with pytest.raises(ValueError, match="dcn="):
        engine.collectives(2, hier, dcn="stale:2")


# -- pipelined bit-exactness ---------------------------------------------


def _sims_digests(mesh, dcn_mode):
    """Checksummed end states of all three sims on ``mesh`` — the
    flat-vs-hier comparison surface (mirrors the DCN worker's sims
    task at a test-budget shape)."""
    from gossip_glomers_tpu.tpu_sim.broadcast import (BroadcastSim,
                                                      make_inject)
    from gossip_glomers_tpu.tpu_sim.counter import CounterSim
    from gossip_glomers_tpu.tpu_sim.kafka import KafkaSim

    out = {}
    n, nv = 16, 16
    sim = BroadcastSim(to_padded_neighbors(grid(n)), n_values=nv,
                       mesh=mesh, dcn_mode=dcn_mode)
    state, rounds = sim.run(make_inject(n, nv))
    out["broadcast"] = {"rounds": int(rounds),
                        "msgs": int(state.msgs),
                        "state": state_digest(state)}

    nc = 8
    deltas = np.arange(1, nc + 1, dtype=np.int32)
    for runner in ("run", "run_fused"):
        sim = CounterSim(nc, mode="cas", seed=7, mesh=mesh,
                         dcn_mode=dcn_mode)
        state = getattr(sim, runner)(
            sim.add(sim.init_state(), deltas), 12)
        out[f"counter_{runner}"] = {"msgs": int(state.msgs),
                                    "state": state_digest(state)}

    rng = np.random.default_rng(0)
    sim = KafkaSim(nc, 4, capacity=32, mesh=mesh, dcn_mode=dcn_mode)
    state = sim.init_state()
    for _ in range(4):
        sk = rng.integers(-1, 4, size=(nc, sim.max_sends)
                          ).astype(np.int32)
        sv = rng.integers(0, 100, size=(nc, sim.max_sends)
                          ).astype(np.int32)
        state = sim.step(state, sk, sv)
    out["kafka"] = {"msgs": int(state.msgs),
                    "state": state_digest(state)}
    return out


def test_pipelined_bit_exact_vs_sync_and_flat():
    hier = pick_mesh_2d(hosts=2)
    flat = pick_mesh()
    assert hier is not None and flat is not None
    hier_pipe = _sims_digests(hier, "pipelined")
    # vs the synchronous twin on the SAME mesh: the half-block
    # decomposition reassociates only integer operands — bit-exact
    assert hier_pipe == _sims_digests(hier, "sync")
    # vs the flat mesh where pipelining is a structural no-op: the
    # hierarchy itself changes no bit either
    assert hier_pipe == _sims_digests(flat, "pipelined")


def test_pipelined_parity_three_hosts():
    # H=3: a NON-power-of-two hosts axis (the OR exchange falls back
    # to the ring schedule; 2 devices per host) vs the flat 6-device
    # mesh — the host-count blindness pin the 2-host CI cluster and
    # the 2-D pick_mesh_2d default cannot cover
    devices = jax.devices()
    assert len(devices) >= 6
    hier3 = Mesh(np.array(devices[:6]).reshape(3, 2),
                 ("hosts", "nodes"))
    flat6 = Mesh(np.array(devices[:6]), ("nodes",))
    res = {}
    for name, mesh, mode in (("h3_sync", hier3, "sync"),
                             ("h3_pipe", hier3, "pipelined"),
                             ("flat", flat6, "pipelined")):
        from gossip_glomers_tpu.tpu_sim.broadcast import (
            BroadcastSim, make_inject)
        from gossip_glomers_tpu.tpu_sim.counter import CounterSim

        n, nv = 12, 8
        sim = BroadcastSim(to_padded_neighbors(grid(n)), n_values=nv,
                           mesh=mesh, dcn_mode=mode)
        state, rounds = sim.run(make_inject(n, nv))
        bd = {"rounds": int(rounds), "msgs": int(state.msgs),
              "state": state_digest(state)}
        csim = CounterSim(n, mode="cas", seed=7, mesh=mesh,
                          dcn_mode=mode)
        cstate = csim.run(
            csim.add(csim.init_state(),
                     np.arange(1, n + 1, dtype=np.int32)), 10)
        res[name] = {"broadcast": bd,
                     "counter": {"msgs": int(cstate.msgs),
                                 "state": state_digest(cstate)}}
    assert res["h3_pipe"] == res["h3_sync"]
    assert res["h3_pipe"] == res["flat"]


# -- certified bounded staleness -----------------------------------------


def test_stale_counter_bounded_delay_zero_loss():
    mesh = pick_mesh_2d(hosts=2)
    assert mesh is not None
    runs = {}
    for label, mode in (("sync", "sync"), ("stale", "stale:4")):
        runs[label] = run_counter_nemesis(
            STALE_SPEC, mode="allreduce", mesh=mesh,
            max_recovery_rounds=32, dcn_mode=mode)
        assert runs[label]["ok"], runs[label]
        assert runs[label]["n_lost_writes"] == 0
        assert runs[label]["kv"] == runs[label]["acked_sum"]
    delay = (runs["stale"]["converged_round"]
             - runs["sync"]["converged_round"])
    # the deferred-delivery carry is REAL (delay >= 1) and bounded
    assert 1 <= delay <= 4, runs
    ok, d = check_staleness_bound(
        stale_k=4,
        sync_converged_round=runs["sync"]["converged_round"],
        stale_converged_round=runs["stale"]["converged_round"],
        lost_writes=[],
        recovery=(runs["stale"]["ok"],
                  {"converged_round": runs["stale"]["converged_round"]}))
    assert ok, d
    # the planted violation: the SAME measured rounds against k=1
    # must fail and name the violating round
    ok, d = check_staleness_bound(
        stale_k=1,
        sync_converged_round=runs["sync"]["converged_round"],
        stale_converged_round=runs["stale"]["converged_round"],
        lost_writes=[])
    assert not ok
    assert d["violating_round"] == runs["stale"]["converged_round"]


def test_stale_flight_bundle_replays_mode(tmp_path):
    from gossip_glomers_tpu.harness.observe import (load_bundle,
                                                    replay_bundle)

    mesh = pick_mesh_2d(hosts=2)
    # a 1-round recovery budget the stale run cannot meet (its carry
    # needs the refresh rounds the sync twin doesn't): the failure
    # writes a flight bundle recording the mode
    res = run_counter_nemesis(
        STALE_SPEC, mode="allreduce", mesh=mesh,
        max_recovery_rounds=1, dcn_mode="stale:4",
        observe_dir=str(tmp_path))
    assert not res["ok"]
    bundles = sorted(tmp_path.glob("*.json"))
    assert bundles, "failing run must write a flight bundle"
    bundle = load_bundle(str(bundles[0]))
    assert bundle["runner_kw"]["dcn_mode"] == "stale:4"
    # replay needs the hierarchical mesh threaded back in — and must
    # reproduce the identical verdict
    replayed = replay_bundle(str(bundles[0]), mesh=mesh)
    assert replayed["ok"] == res["ok"]
    assert replayed["converged_round"] == res["converged_round"]
    # the sync twin PASSES the same 1-round budget: the bundle's
    # failure is the staleness lag itself, not the spec
    sync = run_counter_nemesis(STALE_SPEC, mode="allreduce",
                               mesh=mesh, max_recovery_rounds=1,
                               dcn_mode="sync")
    assert sync["ok"], sync
